"""Audit log + deterministic replay: segment rotation, crash-consistent
loading, the digest chain, and bit-exact re-answering of recorded
requests.

The two load-bearing properties, each pinned by a randomized test:

* **crash consistency** — truncating the final segment at ANY byte
  offset loads to the last complete record and keeps replaying (a
  kill-mid-write can cost at most the record being written);
* **replay bit-exactness** — record randomized generations with
  interleaved sweep/explain/fit requests, reload the log fresh, and
  every recorded request re-answers to its recorded canonical digest —
  both semantics modes, including Q1-overwrite, unhealthy/phantom and
  taint-masked rows.
"""

import dataclasses
import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.audit import (
    AuditError,
    AuditLog,
    AuditReader,
    Replayer,
)
from kubernetesclustercapacity_tpu.fixtures import synthetic_fixture
from kubernetesclustercapacity_tpu.service import (
    CapacityClient,
    CapacityServer,
)
from kubernetesclustercapacity_tpu.snapshot import (
    snapshot_from_fixture,
    synthetic_snapshot,
)
from kubernetesclustercapacity_tpu.timeline.diff import snapshot_digest

_ARRAY_FIELDS = (
    "alloc_cpu_milli", "alloc_mem_bytes", "alloc_pods",
    "used_cpu_req_milli", "used_cpu_lim_milli", "used_mem_req_bytes",
    "used_mem_lim_bytes", "pods_count", "healthy",
)


def _drop_rows(snap, drop):
    keep = [i for i in range(snap.n_nodes) if i not in set(drop)]
    sel = np.asarray(keep, dtype=np.int64)
    return dataclasses.replace(
        snap,
        names=[snap.names[i] for i in keep],
        **{f: np.asarray(getattr(snap, f))[sel] for f in _ARRAY_FIELDS},
        labels=[snap.labels[i] for i in keep] if snap.labels else [],
        taints=[snap.taints[i] for i in keep] if snap.taints else [],
        node_log=[],
        pod_cpu_errs=[[] for _ in keep],
    )


def _append_row(snap, name, *, cpu=4000, mem=8 << 30, pods=110):
    def cat(f, v):
        return np.concatenate(
            [np.asarray(getattr(snap, f)), np.asarray([v])]
        ).astype(np.asarray(getattr(snap, f)).dtype)

    vals = {
        "alloc_cpu_milli": cpu, "alloc_mem_bytes": mem, "alloc_pods": pods,
        "used_cpu_req_milli": cpu // 4, "used_cpu_lim_milli": cpu // 2,
        "used_mem_req_bytes": mem // 4, "used_mem_lim_bytes": mem // 2,
        "pods_count": 3, "healthy": True,
    }
    return dataclasses.replace(
        snap,
        names=snap.names + [name],
        **{f: cat(f, vals[f]) for f in _ARRAY_FIELDS},
        labels=(snap.labels + [{}]) if snap.labels else [],
        taints=(snap.taints + [[]]) if snap.taints else [],
        node_log=[],
        pod_cpu_errs=[],
    )


def _perturb(snap, rng):
    """One randomized generation step: mutate a column, and sometimes
    drop or add rows (drop can hit phantom/duplicate-key rows)."""
    out = snap
    move = rng.integers(0, 4)
    if move == 0 and out.n_nodes > 4:
        out = _drop_rows(out, [int(rng.integers(0, out.n_nodes))])
    elif move == 1:
        out = _append_row(out, f"grown-{int(rng.integers(0, 1 << 16))}")
    arr = np.asarray(out.alloc_cpu_milli).copy()
    i = int(rng.integers(0, out.n_nodes))
    arr[i] = max(int(arr[i] * 0.8), 1)
    out = dataclasses.replace(out, alloc_cpu_milli=arr)
    if rng.integers(0, 3) == 0:
        h = np.asarray(out.healthy).copy()
        j = int(rng.integers(0, out.n_nodes))
        h[j] = not h[j]
        out = dataclasses.replace(out, healthy=h)
    return out


def _fixture_snapshot(mode, seed=5):
    """A fixture-derived snapshot with the awkward rows: unhealthy →
    phantom/duplicate "" keys (reference) or masked-but-real rows
    (strict), plus NoSchedule taints the strict implicit mask zeroes."""
    fx = synthetic_fixture(
        24, seed=seed, unhealthy_frac=0.2, taint_frac=0.3,
        unscheduled_running_pods=3,
    )
    return snapshot_from_fixture(fx, semantics=mode)


class TestAuditLogMechanics:
    def test_checkpoint_and_diff_cadence(self, tmp_path):
        log = AuditLog(str(tmp_path / "a"), checkpoint_every=2)
        snap = synthetic_snapshot(8, seed=1)
        for gen in range(1, 6):
            log.record_generation(
                dataclasses.replace(
                    snap,
                    pods_count=np.asarray(snap.pods_count) + gen,
                ),
                gen,
            )
        log.close()
        reader = AuditReader.load(str(tmp_path / "a"))
        kinds = [r["kind"] for r in reader.generations()]
        # first is always a checkpoint, then every 2nd generation.
        assert kinds == ["checkpoint", "diff", "diff", "checkpoint", "diff"]
        # the chain verifies end to end
        assert reader.verify_chain() == [1, 2, 3, 4, 5]

    def test_segment_rotation_and_cross_segment_refs(self, tmp_path):
        d = str(tmp_path / "a")
        log = AuditLog(d, segment_max_bytes=600, checkpoint_every=4)
        snap = synthetic_snapshot(6, seed=2)
        refs = []
        for gen in range(1, 5):
            log.record_generation(snap, gen)
            refs.append(
                log.record_request(
                    op="sweep",
                    args={"random": {"n": 2, "seed": gen}},
                    generation=gen,
                    status="ok",
                    result={"totals": [gen], "schedulable": [True]},
                )
            )
        log.close()
        segments = sorted(f for f in os.listdir(d) if f.endswith(".jsonl"))
        assert len(segments) > 1  # the cap actually rotated
        reader = AuditReader.load(d)
        assert len(reader.requests()) == 4
        # every ref resolves to its own record, across segment files
        ref_segments = {r.rpartition(":")[0] for r in refs}
        assert len(ref_segments) > 1 and ref_segments <= set(segments)
        for gen, ref in enumerate(refs, start=1):
            rec = reader.record_at(ref)
            assert rec["op"] == "sweep"
            assert rec["args"]["random"]["seed"] == gen

    def test_reopen_never_appends_to_an_old_segment(self, tmp_path):
        d = str(tmp_path / "a")
        snap = synthetic_snapshot(4, seed=3)
        with AuditLog(d) as log:
            log.record_generation(snap, 1)
        with AuditLog(d) as log:
            log.record_generation(snap, 2)
        segments = sorted(f for f in os.listdir(d) if f.endswith(".jsonl"))
        assert segments == ["audit-000001.jsonl", "audit-000002.jsonl"]
        # the second session had no prior summary → a fresh checkpoint,
        # so the reader can reconstruct both generations
        reader = AuditReader.load(d)
        assert [r["kind"] for r in reader.generations()] == [
            "checkpoint", "checkpoint",
        ]
        assert reader.verify_chain() == [1, 2]

    def test_stats_and_validation(self, tmp_path):
        with pytest.raises(ValueError):
            AuditLog(str(tmp_path / "x"), checkpoint_every=0)
        with pytest.raises(ValueError):
            AuditLog(str(tmp_path / "x"), segment_max_bytes=0)
        log = AuditLog(str(tmp_path / "a"))
        snap = synthetic_snapshot(4, seed=4)
        log.record_generation(snap, 1)
        ref = log.generation_ref(1)
        assert ref and ref.startswith("audit-000001.jsonl:")
        st = log.stats()
        assert st["records"] == 2  # header + checkpoint
        assert st["by_kind"] == {"segment_header": 1, "checkpoint": 1}
        assert st["last_generation"] == 1
        log.close()
        with pytest.raises(AuditError):
            log.record_request(
                op="sweep", args={}, generation=1, status="ok"
            )

    def test_load_errors(self, tmp_path):
        with pytest.raises(AuditError):
            AuditReader.load(str(tmp_path / "nope"))
        os.makedirs(str(tmp_path / "empty"))
        with pytest.raises(AuditError):
            AuditReader.load(str(tmp_path / "empty"))


class TestCrashConsistency:
    """Satellite: kill-mid-write simulation — truncating the last
    segment at arbitrary byte offsets must load to the last complete
    record and keep replaying."""

    def _build(self, tmp_path):
        d = str(tmp_path / "log")
        log = AuditLog(d, checkpoint_every=3)
        snap = synthetic_snapshot(6, seed=9)
        rng = np.random.default_rng(9)
        for gen in range(1, 5):
            log.record_generation(snap, gen)
            log.record_request(
                op="sweep",
                args={"random": {"n": 2, "seed": gen}},
                generation=gen,
                status="ok",
                result={"totals": [1, 2], "schedulable": [True, False]},
            )
            snap = _perturb(snap, rng)
        log.close()
        return d

    def test_truncate_tail_at_arbitrary_offsets(self, tmp_path):
        d = self._build(tmp_path)
        (seg,) = [
            f
            for f in sorted(os.listdir(d))
            if f.endswith(".jsonl")
        ][-1:]
        full_bytes = open(os.path.join(d, seg), "rb").read()
        full = AuditReader.load(d)
        full_count = len(full.records)
        # Complete-line boundaries in the final segment, for the
        # expected-prefix oracle.
        boundaries = [
            i + 1 for i, b in enumerate(full_bytes) if b == ord("\n")
        ]
        rng = np.random.default_rng(17)
        cuts = sorted(
            {int(c) for c in rng.integers(1, len(full_bytes), size=25)}
        )
        for cut in cuts:
            case = str(tmp_path / f"cut-{cut}")
            shutil.copytree(d, case)
            with open(os.path.join(case, seg), "r+b") as fh:
                fh.truncate(cut)
            reader = AuditReader.load(case)  # must never raise
            complete = sum(1 for b in boundaries if b <= cut)
            expected = [
                r for r in full.records
                if r["_ref"].rpartition(":")[0] != seg
            ]
            tail = [
                r for r in full.records
                if r["_ref"].rpartition(":")[0] == seg
            ]
            expected += tail[:complete]
            assert [r["_ref"] for r in reader.records] == [
                r["_ref"] for r in expected
            ]
            assert reader.recovered_tail == (
                1 if len(reader.records) < full_count and cut not in
                boundaries else reader.recovered_tail
            )
            # ...and the surviving history still replays: reconstruct
            # the newest generation the truncated log still holds.
            gens = reader.generations()
            if gens:
                snap = reader.snapshot_at(gens[-1]["generation"])
                assert snapshot_digest(snap) == gens[-1]["digest"]

    def test_corruption_before_the_tail_is_fatal(self, tmp_path):
        d = self._build(tmp_path)
        (seg,) = [
            f for f in sorted(os.listdir(d)) if f.endswith(".jsonl")
        ][-1:]
        path = os.path.join(d, seg)
        data = open(path, "rb").read()
        first_nl = data.index(b"\n")
        # Flip a byte inside the FIRST record: mid-file damage is a
        # corruption diagnosis, never silently skipped history.
        patched = b"\x00" + data[1:]
        with open(path, "wb") as fh:
            fh.write(patched)
        assert first_nl < len(data) - 1  # not the tail
        with pytest.raises(AuditError, match="corrupt"):
            AuditReader.load(d)


class TestReplayBitExact:
    """Acceptance: record N randomized generations + interleaved
    sweep/explain (and plain fit) requests, reload fresh, re-answer
    every one identically — both semantics modes, Q1/unhealthy/masked
    fixtures included."""

    @pytest.mark.parametrize("mode", ["reference", "strict"])
    def test_randomized_generations_replay_clean(self, tmp_path, mode):
        d = str(tmp_path / f"audit-{mode}")
        audit = AuditLog(d, checkpoint_every=2, segment_max_bytes=4096)
        snap = _fixture_snapshot(mode)
        server = CapacityServer(
            snap, port=0, batch_window_ms=0.0, audit_log=audit
        )
        rng = np.random.default_rng(42)
        requests = 0
        try:
            for gen in range(5):
                # Tiny requests force fit >= alloc_pods → the Q1
                # overwrite (reference) / the slots clamp (strict).
                server.dispatch(
                    {
                        "op": "sweep",
                        "cpu_request_milli": [1, 50, 100000],
                        "mem_request_bytes": [1, 10**6, 10**12],
                        "replicas": [1, 5, 2],
                    }
                )
                server.dispatch(
                    {"op": "sweep", "random": {"n": 4, "seed": gen}}
                )
                server.dispatch(
                    {
                        "op": "explain",
                        "cpuRequests": f"{int(rng.integers(1, 8))}00m",
                        "memRequests": "512mb",
                    }
                )
                server.dispatch(
                    {"op": "fit", "cpuRequests": "250m", "output": "json"}
                )
                requests += 4
                server.replace_snapshot(
                    _perturb(server.snapshot, rng)
                )
        finally:
            server.shutdown()
            audit.close()
        reader = AuditReader.load(d)
        assert reader.recovered_tail == 0
        with Replayer(reader) as replayer:
            result = replayer.replay_all()
        assert result["chain_error"] is None
        assert result["generations_verified"] == list(range(1, 7))
        assert result["counts"] == {
            "ok": requests, "mismatch": 0, "skipped": 0, "error": 0,
        }
        assert result["clean"]

    def test_error_requests_replay_to_the_same_error(self, tmp_path):
        d = str(tmp_path / "audit")
        audit = AuditLog(d)
        server = CapacityServer(
            synthetic_snapshot(6, seed=1), port=0, batch_window_ms=0.0,
            audit_log=audit,
        )
        try:
            with pytest.raises(ValueError):
                server.dispatch({"op": "fit", "cpuRequests": "0"})
        finally:
            server.shutdown()
            audit.close()
        reader = AuditReader.load(d)
        (rec,) = reader.requests()
        assert rec["status"] == "error"
        with Replayer(reader) as replayer:
            outcome = replayer.replay_record(rec)
        assert outcome["status"] == "ok"
        assert "nonzero" in outcome["replayed_error"]

    def test_tampered_result_digest_is_a_mismatch(self, tmp_path):
        d = str(tmp_path / "audit")
        audit = AuditLog(d)
        server = CapacityServer(
            synthetic_snapshot(6, seed=1), port=0, batch_window_ms=0.0,
            audit_log=audit,
        )
        try:
            server.dispatch({"op": "sweep", "random": {"n": 2, "seed": 0}})
        finally:
            server.shutdown()
            audit.close()
        (seg,) = [f for f in os.listdir(d) if f.endswith(".jsonl")]
        path = os.path.join(d, seg)
        lines = open(path, encoding="utf-8").read().splitlines()
        out = []
        for ln in lines:
            rec = json.loads(ln)
            if rec.get("kind") == "request":
                rec["result_digest"] = "0" * 16
            out.append(json.dumps(rec, sort_keys=True))
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(out) + "\n")
        reader = AuditReader.load(d)
        with Replayer(reader) as replayer:
            result = replayer.replay_all()
        assert result["counts"]["mismatch"] == 1
        assert not result["clean"]

    def test_tampered_state_breaks_the_digest_chain(self, tmp_path):
        d = str(tmp_path / "audit")
        audit = AuditLog(d)
        snap = synthetic_snapshot(6, seed=1)
        audit.record_generation(snap, 1)
        audit.record_generation(
            dataclasses.replace(
                snap, pods_count=np.asarray(snap.pods_count) + 1
            ),
            2,
        )
        audit.close()
        (seg,) = [f for f in os.listdir(d) if f.endswith(".jsonl")]
        path = os.path.join(d, seg)
        lines = open(path, encoding="utf-8").read().splitlines()
        out = []
        for ln in lines:
            rec = json.loads(ln)
            if rec.get("kind") == "checkpoint":
                rec["rows"][0][0] += 1  # silent state edit
            out.append(json.dumps(rec, sort_keys=True))
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(out) + "\n")
        reader = AuditReader.load(d)
        with pytest.raises(AuditError, match="digest"):
            reader.verify_chain()

    def test_fixture_dependent_requests_are_skipped_not_wrong(
        self, tmp_path
    ):
        d = str(tmp_path / "audit")
        audit = AuditLog(d)
        fx = synthetic_fixture(8, seed=3)
        snap = snapshot_from_fixture(fx, semantics="strict")
        server = CapacityServer(
            snap, port=0, batch_window_ms=0.0, fixture=fx,
            audit_log=audit,
        )
        try:
            server.dispatch(
                {
                    "op": "fit",
                    "cpuRequests": "250m",
                    "tolerations": [{"operator": "Exists"}],
                }
            )
            server.dispatch(
                {"op": "place", "cpuRequests": "250m", "replicas": "3"}
            )
        finally:
            server.shutdown()
            audit.close()
        reader = AuditReader.load(d)
        with Replayer(reader) as replayer:
            result = replayer.replay_all()
        assert result["counts"]["skipped"] == 2
        assert result["counts"]["mismatch"] == 0
        assert result["clean"]


class TestAuditService:
    """Wire-level round trip: dump → audit_ref → kccap -replay."""

    def _serve(self, tmp_path):
        d = str(tmp_path / "audit")
        audit = AuditLog(d)
        server = CapacityServer(
            synthetic_snapshot(10, seed=6), port=0, audit_log=audit
        )
        server.start()
        return d, audit, server

    def test_flight_records_carry_audit_refs_that_resolve(self, tmp_path):
        d, audit, server = self._serve(tmp_path)
        try:
            with CapacityClient(*server.address) as c:
                c.sweep(random={"n": 2, "seed": 1})
                c.ping()  # diagnostics are not audited
                dump = c.dump()
                status = c.audit_status()
        finally:
            server.shutdown()
            audit.close()
        by_op = {r["op"]: r for r in dump["records"]}
        ref = by_op["sweep"]["audit_ref"]
        assert ":" in ref
        assert "audit_ref" not in by_op["ping"]
        assert status["enabled"] and status["log"]["records"] >= 2
        reader = AuditReader.load(d)
        rec = reader.record_at(ref)
        assert rec["op"] == "sweep"
        assert rec["args"] == {"random": {"n": 2, "seed": 1}}
        # …and the ref pastes into the CLI (exit 0 = replay verified).
        from kubernetesclustercapacity_tpu.cli import main as cli_main

        assert cli_main(["-replay", d, "-replay-ref", ref]) == 0

    def test_cli_replay_all_and_generation(self, tmp_path, capsys):
        d, audit, server = self._serve(tmp_path)
        try:
            with CapacityClient(*server.address) as c:
                c.sweep(random={"n": 2, "seed": 1})
                c.explain(cpuRequests="500m")
        finally:
            server.shutdown()
            audit.close()
        from kubernetesclustercapacity_tpu.cli import main as cli_main

        assert cli_main(["-replay", d]) == 0
        out = capsys.readouterr().out
        assert "CLEAN" in out
        assert cli_main(["-replay", d, "-replay-generation", "1"]) == 0
        assert "verified" in capsys.readouterr().out
        assert cli_main(["-replay", d, "-output", "json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["clean"] is True
        assert cli_main(["-replay", str(tmp_path / "missing")]) == 1

    def test_auth_token_never_lands_in_the_audit_log(self, tmp_path):
        d = str(tmp_path / "audit")
        audit = AuditLog(d)
        server = CapacityServer(
            synthetic_snapshot(6, seed=6), port=0, audit_log=audit,
            auth_token="sekrit-token",
        )
        server.start()
        try:
            with CapacityClient(
                *server.address, token="sekrit-token"
            ) as c:
                c.sweep(random={"n": 2, "seed": 1})
        finally:
            server.shutdown()
            audit.close()
        (seg,) = [f for f in os.listdir(d) if f.endswith(".jsonl")]
        raw = open(os.path.join(d, seg), encoding="utf-8").read()
        assert "sekrit-token" not in raw


def test_replay_in_a_fresh_process(tmp_path):
    """Acceptance: the audit log reloads in a FRESH interpreter and
    re-answers every recorded request identically (kccap -replay's
    real deployment shape)."""
    d = str(tmp_path / "audit")
    audit = AuditLog(d, checkpoint_every=2)
    snap = _fixture_snapshot("reference")
    server = CapacityServer(
        snap, port=0, batch_window_ms=0.0, audit_log=audit
    )
    rng = np.random.default_rng(7)
    try:
        for gen in range(3):
            server.dispatch({"op": "sweep", "random": {"n": 3, "seed": gen}})
            server.dispatch({"op": "explain", "cpuRequests": "750m"})
            server.replace_snapshot(_perturb(server.snapshot, rng))
    finally:
        server.shutdown()
        audit.close()
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from kubernetesclustercapacity_tpu.cli import main; "
            f"raise SystemExit(main(['-replay', {d!r}]))",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CLEAN" in proc.stdout
