"""Rule-sensitivity tests: every analyzer rule family must fire on the
deliberately-broken fixture package — at exactly the marked file:line —
and must fire on NOTHING else (precision is the other half of a usable
linter).

The fixtures under ``tests/lint_fixtures/fixture_pkg`` carry
``# expect: rule[, rule]`` markers: trailing on the offending line, or
standalone on the line above (same placement grammar as the
``kccap: lint-ok[...]`` suppressions).  The tests derive the expected
``(rule, path, line)`` set from those markers, so fixture edits cannot
drift from the assertions.
"""

import os
import re

import pytest

from kubernetesclustercapacity_tpu.analysis.engine import (
    Analyzer,
    Baseline,
    Project,
)

FIXTURE_ROOT = os.path.join(os.path.dirname(__file__), "lint_fixtures")
FIXTURE_PKG = os.path.join(FIXTURE_ROOT, "fixture_pkg")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([a-z0-9\-]+(?:\s*,\s*[a-z0-9\-]+)*)")


@pytest.fixture(scope="module")
def result():
    return Analyzer(Project(FIXTURE_PKG)).run()


def _expected() -> set[tuple[str, str, int]]:
    out: set[tuple[str, str, int]] = set()
    for root, dirs, files in os.walk(FIXTURE_PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, FIXTURE_ROOT).replace(os.sep, "/")
            with open(path, encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, start=1):
                    m = _EXPECT_RE.search(line)
                    if not m:
                        continue
                    target = (
                        lineno + 1
                        if line.lstrip().startswith("#")
                        else lineno
                    )
                    for rule in m.group(1).split(","):
                        out.add((rule.strip(), rel, target))
    return out


def test_marker_scan_is_not_vacuous():
    expected = _expected()
    assert len(expected) >= 15
    assert any(r == "jit-purity" for r, _, _ in expected)
    assert any(r == "lock-discipline" for r, _, _ in expected)
    assert any(r.startswith("surface-") for r, _, _ in expected)


def test_every_marked_line_fires(result):
    got = {(f.rule, f.path, f.line) for f in result.findings}
    missing = _expected() - got
    assert not missing, f"rules failed to fire at marked lines: {sorted(missing)}"


def test_no_unmarked_findings(result):
    """Precision: the analyzer reports nothing the fixtures did not
    deliberately plant."""
    extra = {(f.rule, f.path, f.line) for f in result.findings} - _expected()
    assert not extra, f"unexpected findings: {sorted(extra)}"


def test_every_rule_family_represented(result):
    rules = {f.rule for f in result.findings}
    assert "jit-purity" in rules
    assert "lock-discipline" in rules
    assert {"surface-metric", "surface-env", "surface-op", "surface-flag"} <= rules
    assert "hygiene-unused-import" in rules
    assert "hygiene-thread-death" in rules
    assert "lock-order" in rules


def test_jit_purity_covers_every_category(result):
    cats = {
        f.message.split(":", 1)[0]
        for f in result.findings
        if f.rule == "jit-purity"
    }
    assert cats == {
        "host-subsystem", "clock", "io", "random", "lock",
        "host-callback", "numpy-on-traced", "traced-coercion",
    }


def test_transitive_reachability_names_the_chain(result):
    [f] = [
        f
        for f in result.findings
        if f.rule == "jit-purity"
        and "time.time" in f.message
        and f.path.endswith("bad_jit.py")
    ]
    assert "transitive_root" in f.message and "_helper" in f.message


def test_surface_op_flags_both_failure_modes(result):
    ops = [f for f in result.findings if f.rule == "surface-op"]
    assert len(ops) == 2
    assert all(f.symbol.startswith("mystery") for f in ops)
    assert {f.symbol for f in ops} == {"mystery", "mystery:client"}


def test_documented_names_do_not_fire(result):
    text = " ".join(f.message for f in result.findings)
    assert "kccap_fixture_documented_total" not in text
    assert "KCCAP_FIXTURE_DOCUMENTED" not in text
    assert "-documented-flag" not in text
    assert "`ping`" not in text


def test_inline_suppression_admits_exactly_the_marked_line(result):
    sup = [f for f in result.suppressed if f.rule == "lock-discipline"]
    assert len(sup) == 1
    assert sup[0].symbol == "Racy._errors@suppressed_read"
    live = {f.symbol for f in result.findings if f.rule == "lock-discipline"}
    assert sup[0].symbol not in live


def test_locked_suffix_convention_is_honored(result):
    assert not any(
        "_total_locked" in f.symbol
        for f in result.findings
        if f.rule == "lock-discipline"
    )


def test_lock_order_cycle_reports_both_edges(result):
    """The planted inversion yields one finding per participating edge
    — each anchored at its own acquisition order's exact site — and
    the consistently-ordered control class yields nothing."""
    edges = [f for f in result.findings if f.rule == "lock-order"]
    assert len(edges) == 2
    symbols = {f.symbol for f in edges}
    a = "fixture_pkg.bad_lockorder:_LOCK_A"
    b = "fixture_pkg.bad_lockorder:_LOCK_B"
    assert symbols == {f"{a}->{b}", f"{b}->{a}"}
    # The interprocedural edge names the callee and its inner site.
    [inter] = [f for f in edges if f.symbol == f"{a}->{b}"]
    assert "_grab_b" in inter.message
    assert "bad_lockorder.py:17" in inter.message
    # Each message points at the opposing order's site.
    [lex] = [f for f in edges if f.symbol == f"{b}->{a}"]
    assert f"bad_lockorder.py:{inter.line}" in lex.message
    assert "Ordered" not in " ".join(f.message for f in edges)


def test_thread_death_resolves_module_and_method_targets(result):
    hits = {
        f.symbol for f in result.findings if f.rule == "hygiene-thread-death"
    }
    assert "fragile_worker" in hits
    assert "Worker.self._run" in hits
    # The protected control worker must NOT fire.
    assert not any("safe_worker" in s for s in hits)


def test_wraps_decorated_closure_becomes_jit_root(result):
    """``jax.jit(wrapper)`` where wrapper is a functools.wraps-decorated
    closure: the closure is a root and its body is purity-checked."""
    [f] = [
        f
        for f in result.findings
        if f.rule == "jit-purity" and "_decorate.wrapper" in f.message
    ]
    assert "time.time" in f.message


def test_lambda_passed_to_jit_marks_referenced_helper(result):
    """``jax.jit(lambda x: _lam_helper(x))`` at module level: the
    helper referenced from the lambda body is a root."""
    [f] = [
        f
        for f in result.findings
        if f.rule == "jit-purity" and "_lam_helper" in f.message
    ]
    assert "time.perf_counter" in f.message


def test_threaded_class_inference_through_inheritance(result):
    """``Derived`` acquires ``self._mu`` — ctor-proven only in its
    base, under a name the lock-looking heuristic rejects — and its
    unguarded read fires at the exact marked line."""
    [f] = [
        f
        for f in result.findings
        if f.rule == "lock-discipline" and f.symbol == "Derived._hits@racy"
    ]
    assert "self._mu" in f.message


def test_baseline_round_trip(tmp_path, result):
    path = os.path.join(tmp_path, "baseline.json")
    Baseline.from_findings(
        result.findings, history=["test: accept everything"]
    ).save(path)
    reloaded = Baseline.load(path)
    assert reloaded.history == ["test: accept everything"]
    rerun = Analyzer(Project(FIXTURE_PKG), baseline=reloaded).run()
    assert rerun.clean
    assert len(rerun.baselined) == len(result.findings)


def test_baseline_is_line_independent(result):
    f = result.findings[0]
    moved = type(f)(
        rule=f.rule,
        severity=f.severity,
        path=f.path,
        line=f.line + 40,
        col=0,
        message=f.message,
        symbol=f.symbol,
    )
    bl = Baseline.from_findings([f])
    assert bl.matches(moved)


def test_rules_subset_runs_only_named_families():
    result = Analyzer(Project(FIXTURE_PKG), rules=("lock-discipline",)).run()
    assert result.findings
    assert {f.rule for f in result.findings} == {"lock-discipline"}


def test_unknown_rule_family_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        Analyzer(Project(FIXTURE_PKG), rules=("no-such-rule",))


# -- kccap-lint --diff-baseline (the CI/tier-1 gate mode) ------------------


def test_diff_baseline_prints_only_new_findings(tmp_path, result, capsys):
    from kubernetesclustercapacity_tpu.analysis import cli

    # Baseline everything: the diff must be empty and exit 0, with NO
    # recap of accepted history on stdout.
    bl_path = os.path.join(tmp_path, "bl.json")
    Baseline.from_findings(result.findings).save(bl_path)
    rc = cli.run([FIXTURE_PKG, "--baseline", bl_path, "--diff-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out == ""

    # Drop one entry from the baseline: exactly that finding prints,
    # and the exit flips to 1.
    victim = result.findings[0]
    partial = Baseline.from_findings(result.findings[1:])
    partial.save(bl_path)
    rc = cli.run([FIXTURE_PKG, "--baseline", bl_path, "--diff-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [ln for ln in out.splitlines() if ln]
    assert lines == [victim.render()]
