"""Oracle tests: hand-computed expectations for the kind fixture + quirk coverage."""

import math

import pytest

from kubernetesclustercapacity_tpu.fixtures import load_fixture, synthetic_fixture
from kubernetesclustercapacity_tpu.oracle import (
    ReferencePanic,
    healthy_nodes,
    non_terminated_pods_for_node,
    pod_requests_limits,
    reference_run,
)
from kubernetesclustercapacity_tpu.scenario import (
    Scenario,
    ScenarioError,
    scenario_from_flags,
)

MIB = 1024 * 1024
KIND_ALLOC_MEM = 16368832 * 1024  # "16368832Ki"


@pytest.fixture(scope="module")
def kind_fixture():
    return load_fixture("tests/fixtures/kind-3node.json")


# The reference sample-run spec (README.md:40): 200m/400m CPU, 250mb/500mb mem.
SAMPLE_SCENARIO = scenario_from_flags(
    cpuRequests="200m", cpuLimits="400m", memRequests="250mb", memLimits="500mb",
    replicas="10",
)


class TestScenarioParsing:
    def test_sample_flags(self):
        assert SAMPLE_SCENARIO.cpu_request_milli == 200
        assert SAMPLE_SCENARIO.mem_request_bytes == 250 * MIB
        assert SAMPLE_SCENARIO.replicas == 10
        assert SAMPLE_SCENARIO.cpu_limit_milli == 400
        assert SAMPLE_SCENARIO.mem_limit_bytes == 500 * MIB

    def test_defaults_match_reference(self):
        s = scenario_from_flags()
        assert (s.cpu_request_milli, s.cpu_limit_milli) == (100, 200)
        assert (s.mem_request_bytes, s.mem_limit_bytes) == (100 * MIB, 200 * MIB)
        assert s.replicas == 1

    def test_bad_mem_is_fatal(self):
        with pytest.raises(ScenarioError):
            scenario_from_flags(memRequests="garbage")

    def test_bad_replicas_is_fatal(self):
        with pytest.raises(ScenarioError):
            scenario_from_flags(replicas="ten")

    def test_bad_cpu_silently_zero_then_validate_rejects(self):
        # Reference: unparseable CPU -> 0 -> later div-by-zero panic.  We
        # surface it at validate() instead (SURVEY §2.4 Q8).
        s = scenario_from_flags(cpuRequests="half")
        assert s.cpu_request_milli == 0
        with pytest.raises(ScenarioError):
            s.validate()


class TestHealthyNodes:
    def test_kind_nodes_all_healthy(self, kind_fixture):
        nodes = healthy_nodes(kind_fixture)
        assert [n.name for n in nodes] == [
            "kind-control-plane", "kind-worker", "kind-worker2",
        ]
        for n in nodes:
            assert n.allocatable_cpu == 8000
            assert n.allocatable_memory == KIND_ALLOC_MEM
            assert n.allocatable_pods == 110

    def test_unhealthy_leaves_phantom_zero_node(self, kind_fixture):
        fx = load_fixture("tests/fixtures/kind-3node.json")
        fx["nodes"][1]["conditions"][1]["status"] = "True"  # MemoryPressure
        nodes = healthy_nodes(fx)
        assert nodes[1].name == ""
        assert nodes[1].allocatable_cpu == 0
        assert nodes[1].allocatable_pods == 0

    def test_fewer_than_four_conditions_panics(self):
        # All-False conditions that run out before j=4: Go indexes past the
        # slice end.  (A non-"False" first condition would break early and
        # NOT panic — matching Go's loop order.)
        fx = {"nodes": [{"name": "n", "allocatable": {}, "conditions": [
            {"type": "MemoryPressure", "status": "False"},
            {"type": "DiskPressure", "status": "False"}]}], "pods": []}
        with pytest.raises(ReferencePanic, match="index out of range"):
            healthy_nodes(fx)

    def test_early_break_on_unhealthy_avoids_index_panic(self):
        fx = {"nodes": [{"name": "n", "allocatable": {}, "conditions": [
            {"type": "Ready", "status": "True"}]}], "pods": []}
        nodes = healthy_nodes(fx)  # breaks at j=0, no panic
        assert nodes[0].name == ""

    def test_slice_bug_emulation(self):
        fx = synthetic_fixture(4, seed=1)
        with pytest.raises(ReferencePanic, match="makeslice"):
            healthy_nodes(fx, emulate_slice_bug=True)
        assert len(healthy_nodes(fx)) == 4  # default mode diverges: succeeds

    def test_gi_memory_zeroes_node(self):
        fx = {"nodes": [{"name": "n", "allocatable": {
            "cpu": "4", "memory": "16Gi", "pods": "110"},
            "conditions": [{"type": t, "status": "False"} for t in "abcd"]}],
            "pods": []}
        nodes = healthy_nodes(fx)
        assert nodes[0].allocatable_memory == 0  # Q5: bytefmt rejects Gi -> 0
        assert nodes[0].allocatable_cpu == 4000


class TestPodListing:
    def test_running_only_and_all_namespaces(self, kind_fixture):
        pods = non_terminated_pods_for_node(kind_fixture, "kind-worker")
        names = sorted(p["name"] for p in pods)
        # Succeeded batch job excluded; kube-system + default both included.
        assert names == [
            "coredns-565d847f94-9ttqk", "kube-proxy-kind-worker",
            "web-7f5b8c9d4-abcde",
        ]

    def test_phantom_node_matches_unscheduled(self):
        fx = synthetic_fixture(2, seed=3, unscheduled_running_pods=2)
        orphans = non_terminated_pods_for_node(fx, "")
        assert len(orphans) == 2


class TestPodSums:
    def test_kind_worker_sums(self, kind_fixture):
        pods = non_terminated_pods_for_node(kind_fixture, "kind-worker")
        cpu_lim, cpu_req, mem_lim, mem_req = pod_requests_limits(pods)
        # coredns 100m/70Mi (lim mem 170Mi), proxy nothing,
        # web: containers (500m,512Mi lim 1cpu/1Gi) + (50m,64Mi); init ignored.
        assert cpu_req == 100 + 500 + 50
        assert mem_req == (70 + 512 + 64) * MIB
        assert cpu_lim == 1000
        assert mem_lim == 170 * MIB + 1024 * MIB


class TestReferenceRun:
    def test_kind_sample_run(self, kind_fixture):
        result = reference_run(kind_fixture, SAMPLE_SCENARIO)
        # Hand-computed (see SURVEY §2.2 C8 semantics):
        # control-plane: cpu (8000-650)//200=36, mem (alloc-100Mi)//250Mi=63 -> 36
        # worker:        cpu (8000-650)//200=36, mem (alloc-646Mi)//250Mi=61 -> 36
        # worker2:       cpu (8000-600)//200=37, mem (alloc-582Mi)//250Mi=61 -> 37
        assert result.fits == [36, 36, 37]
        assert result.total_possible_replicas == 109
        assert result.schedulable  # 109 >= 10

    def test_pod_cap_quirk_triggers(self):
        # Empty node, tiny pod budget: fit >= allocatablePods -> capped to
        # allocatablePods - len(pods).
        fx = {"nodes": [{"name": "n", "allocatable": {
            "cpu": "8", "memory": "1048576Ki", "pods": "5"},
            "conditions": [{"type": t, "status": "False"} for t in "abcd"]}],
            "pods": []}
        r = reference_run(fx, Scenario(100, MIB, 1))
        assert r.fits == [5]

    def test_pod_cap_quirk_not_applied_below_threshold(self):
        # SURVEY §2.4 Q1: cap only when fit >= allocatablePods.  110 alloc
        # pods, 50 running 0-request pods, cpu fit 100 -> returns 100 even
        # though only 60 pod slots remain.
        fx = {"nodes": [{"name": "n", "allocatable": {
            "cpu": "10", "memory": "104857600Ki", "pods": "110"},
            "conditions": [{"type": t, "status": "False"} for t in "abcd"]}],
            "pods": [{"name": f"p{i}", "namespace": "default", "nodeName": "n",
                      "phase": "Running", "containers": [{"resources": {}}]}
                     for i in range(50)]}
        r = reference_run(fx, Scenario(100, MIB, 1))
        assert r.fits == [100]

    def test_negative_fit_from_cap(self):
        # alloc_pods=2 but 5 running pods: fit -> 2 - 5 = -3.
        fx = {"nodes": [{"name": "n", "allocatable": {
            "cpu": "64", "memory": "104857600Ki", "pods": "2"},
            "conditions": [{"type": t, "status": "False"} for t in "abcd"]}],
            "pods": [{"name": f"p{i}", "namespace": "d", "nodeName": "n",
                      "phase": "Running", "containers": [{"resources": {}}]}
                     for i in range(5)]}
        r = reference_run(fx, Scenario(100, MIB, 1))
        assert r.fits == [-3]
        assert r.total_possible_replicas == -3

    def test_phantom_node_with_orphan_pods_goes_negative(self):
        fx = synthetic_fixture(
            3, seed=7, unhealthy_frac=1.0, unscheduled_running_pods=4)
        r = reference_run(fx, Scenario(100, MIB, 1))
        # All nodes phantom: fit = min(0,0)=0 >= alloc_pods(0) -> 0 - 4 orphans.
        assert r.fits == [-4, -4, -4]

    def test_full_node_yields_zero_without_division(self):
        # alloc <= used guards the division, so cpu_request=0 does NOT panic
        # when every node is already full (guard order parity).
        fx = {"nodes": [{"name": "n", "allocatable": {
            "cpu": "1", "memory": "1024Ki", "pods": "110"},
            "conditions": [{"type": t, "status": "False"} for t in "abcd"]}],
            "pods": [{"name": "p", "namespace": "d", "nodeName": "n",
                      "phase": "Running", "containers": [{"resources": {
                          "requests": {"cpu": "2", "memory": "1Gi"}}}]}]}
        r = reference_run(fx, Scenario(0, 0, 1))  # zero requests, but guarded
        assert r.fits == [0]

    def test_zero_cpu_request_panics_on_headroom(self, kind_fixture):
        with pytest.raises(ReferencePanic, match="divide by zero"):
            reference_run(kind_fixture, Scenario(0, MIB, 1))

    def test_percentages_use_go_float_semantics(self):
        fx = synthetic_fixture(2, seed=9, unhealthy_frac=1.0)
        r = reference_run(fx, Scenario(100, MIB, 1))
        # Phantom nodes: 0*100/0 -> NaN (not a crash).
        assert math.isnan(r.per_node[0].cpu_request_used_percent)

    def test_verdict_threshold(self, kind_fixture):
        assert reference_run(kind_fixture, SAMPLE_SCENARIO).schedulable
        big = Scenario(200, 250 * MIB, 110)
        assert not reference_run(kind_fixture, big).schedulable  # 109 < 110
        edge = Scenario(200, 250 * MIB, 109)
        assert reference_run(kind_fixture, edge).schedulable  # >= is inclusive
