"""Node-shape compression: grouped sweeps proven bit-exact vs the
ungrouped sequential oracle, in both semantics modes.

The grouped path's contract is the same as every hot-path PR before it:
``(shape, count)`` compression is an *optimization*, never a semantics
change — every test here pins the grouped dispatch element-for-element
against ``fit_arrays_python`` (the bug-compatible sequential walk) or
against the exact ungrouped kernel with ``KCCAP_GROUPING=0``.
"""

import dataclasses
import os

import numpy as np
import pytest

from kubernetesclustercapacity_tpu import devcache
from kubernetesclustercapacity_tpu import snapshot as snapshot_mod
from kubernetesclustercapacity_tpu.explain import explain_snapshot
from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.ops.fit import (
    sweep_grid_multi,
    sweep_grouped_bucketed,
    sweep_snapshot,
)
from kubernetesclustercapacity_tpu.ops.pallas_fit import (
    _sweep_auto_grouped,
    reset_fast_path,
    sweep_snapshot_auto,
)
from kubernetesclustercapacity_tpu.scenario import (
    ScenarioGrid,
    random_scenario_grid,
)
from kubernetesclustercapacity_tpu.snapshot import (
    GROUPING_NODE_FLOOR,
    ClusterSnapshot,
    grouped_for_dispatch,
    synthetic_snapshot,
)

N_DEGENERATE = 2048  # >= GROUPING_NODE_FLOOR, cheap to oracle-walk


@pytest.fixture(autouse=True)
def _restore_group_min_count():
    before = snapshot_mod.group_min_count()
    yield
    snapshot_mod.set_group_min_count(before)


def _degenerate_snapshot(seed=3, n=N_DEGENERATE, shapes=23):
    return synthetic_snapshot(n, seed=seed, shapes=shapes)


def _oracle_fits(snap, grid, mode, node_mask=None):
    """Sequential ground truth: per-scenario fit_arrays_python with the
    kernel's post-epilogue mask zeroing applied on top."""
    out = []
    for j in range(grid.size):
        fits = np.asarray(
            fit_arrays_python(
                snap.alloc_cpu_milli, snap.alloc_mem_bytes,
                snap.alloc_pods, snap.used_cpu_req_milli,
                snap.used_mem_req_bytes, snap.pods_count,
                int(grid.cpu_request_milli[j]),
                int(grid.mem_request_bytes[j]),
                mode=mode, healthy=snap.healthy,
            ),
            dtype=np.int64,
        )
        if node_mask is not None:
            fits = np.where(np.asarray(node_mask, dtype=bool), fits, 0)
        out.append(fits)
    return np.stack(out)


class TestGroupedForm:
    def test_counts_and_index_invert_the_compression(self):
        snap = _degenerate_snapshot()
        g = snap.grouped()
        assert g.n_groups < snap.n_nodes
        assert int(g.count.sum()) == snap.n_nodes
        assert g.group_index.shape == (snap.n_nodes,)
        # expand(gather) reconstructs every per-node column exactly
        for f in ("alloc_cpu_milli", "used_mem_req_bytes", "pods_count"):
            np.testing.assert_array_equal(
                g.expand(getattr(g, f)), np.asarray(getattr(snap, f))
            )
        np.testing.assert_array_equal(
            g.expand(g.healthy), np.asarray(snap.healthy)
        )
        # representative = first node row carrying the shape
        for gi in range(g.n_groups):
            members = g.members(gi)
            assert members.size == int(g.count[gi])
            assert int(g.representative[gi]) == int(members[0])

    def test_memoized_per_snapshot(self):
        snap = _degenerate_snapshot()
        assert snap.grouped() is snap.grouped()

    def test_different_health_never_merges(self):
        # Two rows identical in EVERY resource column, health differs —
        # they must land in distinct groups (and sweep correctly).
        n = 4
        snap = ClusterSnapshot(
            names=[f"n{i}" for i in range(n)],
            alloc_cpu_milli=np.full(n, 4000),
            alloc_mem_bytes=np.full(n, 8 << 30),
            alloc_pods=np.full(n, 110),
            used_cpu_req_milli=np.full(n, 500),
            used_mem_req_bytes=np.full(n, 1 << 30),
            used_cpu_lim_milli=np.zeros(n),
            used_mem_lim_bytes=np.zeros(n),
            pods_count=np.full(n, 3),
            healthy=np.array([True, False, True, False]),
            semantics="strict",
        )
        g = snap.grouped()
        assert g.n_groups == 2
        assert sorted(g.count.tolist()) == [2, 2]

    def test_different_extended_never_merges(self):
        n = 4
        gpu_alloc = np.array([0, 8, 0, 8], dtype=np.int64)
        snap = ClusterSnapshot(
            names=[f"n{i}" for i in range(n)],
            alloc_cpu_milli=np.full(n, 4000),
            alloc_mem_bytes=np.full(n, 8 << 30),
            alloc_pods=np.full(n, 110),
            used_cpu_req_milli=np.full(n, 500),
            used_mem_req_bytes=np.full(n, 1 << 30),
            used_cpu_lim_milli=np.zeros(n),
            used_mem_lim_bytes=np.zeros(n),
            pods_count=np.full(n, 3),
            healthy=np.ones(n, dtype=bool),
            semantics="strict",
            extended={"nvidia.com/gpu": (gpu_alloc, np.zeros(n, np.int64))},
        )
        g = snap.grouped()
        assert g.n_groups == 2
        np.testing.assert_array_equal(
            g.expand(g.extended["nvidia.com/gpu"][0]), gpu_alloc
        )

    def test_dispatch_gates(self, monkeypatch):
        # Small clusters never group; heterogeneous big ones don't pay.
        assert grouped_for_dispatch(synthetic_snapshot(500, seed=1)) is None
        hetero = synthetic_snapshot(GROUPING_NODE_FLOOR + 5, seed=2)
        assert hetero.grouped().compression_ratio < 2
        assert grouped_for_dispatch(hetero) is None
        snap = _degenerate_snapshot()
        assert grouped_for_dispatch(snap) is not None
        # escape hatch
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        assert grouped_for_dispatch(snap) is None
        monkeypatch.delenv("KCCAP_GROUPING")
        # occupancy gate is flag-settable
        snapshot_mod.set_group_min_count(10 ** 6)
        assert grouped_for_dispatch(snap) is None

    def test_effective_counts_fold_the_mask(self):
        snap = _degenerate_snapshot()
        g = snap.grouped()
        mask = np.random.default_rng(5).random(snap.n_nodes) < 0.4
        eff = g.effective_counts(mask)
        assert int(eff.sum()) == int(mask.sum())
        np.testing.assert_array_equal(
            eff, np.bincount(g.group_index[mask], minlength=g.n_groups)
        )
        with pytest.raises(ValueError):
            g.effective_counts(np.ones(3, dtype=bool))


class TestGroupedSweepOracleParity:
    @pytest.mark.parametrize("mode", ("reference", "strict"))
    @pytest.mark.parametrize("seed", (0, 7, 23))
    def test_grouped_equals_sequential_oracle(self, mode, seed):
        snap = _degenerate_snapshot(seed=seed, shapes=17 + seed)
        if mode == "strict":
            # flip some health so the strict zeroing is exercised
            snap.healthy[::11] = False
        grid = random_scenario_grid(12, seed=seed + 1)
        assert grouped_for_dispatch(snap) is not None
        totals, sched, fits = sweep_snapshot(
            snap, grid, mode=mode, return_per_node=True
        )
        expected = _oracle_fits(snap, grid, mode)
        np.testing.assert_array_equal(fits, expected)
        np.testing.assert_array_equal(totals, expected.sum(axis=1))

    def test_q1_overwrite_with_negative_fits(self):
        # Q1: fit >= alloc_pods overwrites with alloc_pods - pods_count,
        # which can be NEGATIVE — count weighting must carry that sign.
        snap = _degenerate_snapshot(seed=9)
        snap.alloc_pods[:] = 3
        snap.pods_count[:] = 7  # overwrite value = -4 on saturated nodes
        grid = ScenarioGrid(
            cpu_request_milli=np.array([1, 100]),
            mem_request_bytes=np.array([1, 1 << 20]),
            replicas=np.array([1, 1]),
        )
        totals, _, fits = sweep_snapshot(
            snap, grid, mode="reference", return_per_node=True
        )
        expected = _oracle_fits(snap, grid, "reference")
        assert (expected < 0).any()  # the adversarial case actually fired
        np.testing.assert_array_equal(fits, expected)
        np.testing.assert_array_equal(totals, expected.sum(axis=1))

    def test_wrapped_negative_carriers(self):
        snap = _degenerate_snapshot(seed=11)
        snap.used_mem_req_bytes[: snap.n_nodes // 2] = -(1 << 40)
        snap.alloc_cpu_milli[::3] = -5  # huge uint64 view
        grid = random_scenario_grid(6, seed=12)
        totals, _, fits = sweep_snapshot(
            snap, grid, mode="reference", return_per_node=True
        )
        expected = _oracle_fits(snap, grid, "reference")
        np.testing.assert_array_equal(fits, expected)
        np.testing.assert_array_equal(totals, expected.sum(axis=1))

    @pytest.mark.parametrize("mode", ("reference", "strict"))
    def test_masked_sweep_matches_oracle(self, mode):
        snap = _degenerate_snapshot(seed=13)
        snap.healthy[::9] = False
        mask = np.random.default_rng(14).random(snap.n_nodes) < 0.6
        grid = random_scenario_grid(8, seed=15)
        totals, _, fits = sweep_snapshot(
            snap, grid, mode=mode, node_mask=mask, return_per_node=True
        )
        expected = _oracle_fits(snap, grid, mode, node_mask=mask)
        np.testing.assert_array_equal(fits, expected)
        np.testing.assert_array_equal(totals, expected.sum(axis=1))

    def test_escape_hatch_restores_ungrouped_path(self, monkeypatch):
        snap = _degenerate_snapshot(seed=17)
        grid = random_scenario_grid(9, seed=18)
        on = sweep_snapshot(snap, grid, return_per_node=True)
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        off = sweep_snapshot(snap, grid, return_per_node=True)
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a, b)

    def test_devcache_off_still_exact(self, monkeypatch):
        snap = _degenerate_snapshot(seed=19)
        grid = random_scenario_grid(7, seed=20)
        on = sweep_snapshot(snap, grid)
        monkeypatch.setenv("KCCAP_DEVCACHE", "0")
        off = sweep_snapshot(snap, grid)
        np.testing.assert_array_equal(on[0], off[0])
        np.testing.assert_array_equal(on[1], off[1])

    def test_extended_resources_group_weighted_multi(self):
        # R-dim kernel over grouped rows + count weighting == per-node.
        snap = _degenerate_snapshot(seed=21, shapes=11)
        n = snap.n_nodes
        rng = np.random.default_rng(22)
        gpu = rng.integers(0, 3, 11)[snap.grouped().group_index]
        snap2 = dataclasses.replace(
            snap,
            semantics="strict",
            extended={
                "nvidia.com/gpu": (gpu, np.zeros(n, dtype=np.int64))
            },
        )
        g = snap2.grouped()
        reqs_sr = np.stack(
            [
                rng.integers(100, 2000, 5),
                rng.integers(1 << 20, 1 << 30, 5),
                rng.integers(0, 2, 5),
            ],
            axis=1,
        ).astype(np.int64)
        replicas = np.ones(5, dtype=np.int64)
        alloc_rn, used_rn = (
            np.stack([snap2.alloc_cpu_milli, snap2.alloc_mem_bytes, gpu]),
            np.stack(
                [
                    snap2.used_cpu_req_milli,
                    snap2.used_mem_req_bytes,
                    np.zeros(n, dtype=np.int64),
                ]
            ),
        )
        per_node = np.asarray(
            sweep_grid_multi(
                alloc_rn, used_rn, snap2.alloc_pods, snap2.pods_count,
                snap2.healthy, reqs_sr, replicas, mode="strict",
            )[0]
        )
        galloc = np.stack(
            [g.alloc_cpu_milli, g.alloc_mem_bytes,
             g.extended["nvidia.com/gpu"][0]]
        )
        gused = np.stack(
            [g.used_cpu_req_milli, g.used_mem_req_bytes,
             g.extended["nvidia.com/gpu"][1]]
        )
        _, _, gfits = sweep_grid_multi(
            galloc, gused, g.alloc_pods, g.pods_count, g.healthy,
            reqs_sr, replicas, mode="strict", return_per_node=True,
        )
        grouped_totals = (np.asarray(gfits) * g.count[None, :]).sum(axis=1)
        np.testing.assert_array_equal(grouped_totals, per_node)


class TestGroupedAutoDispatch:
    def test_auto_path_equals_oracle_and_names_grouped_kernel(self):
        reset_fast_path()
        try:
            snap = _degenerate_snapshot(seed=25)
            grid = random_scenario_grid(10, seed=26)
            totals, sched, kernel = sweep_snapshot_auto(snap, grid)
            assert kernel.endswith("_grouped")
            expected = _oracle_fits(snap, grid, "reference").sum(axis=1)
            np.testing.assert_array_equal(totals, expected)
        finally:
            reset_fast_path()

    @pytest.mark.parametrize("mode", ("reference", "strict"))
    def test_fused_grouped_attempt_stays_exact(self, mode):
        # Whether the fused grouped kernel runs or the breaker degrades
        # it to the exact grouped path (this host's Pallas interpret
        # path is known-broken), the ANSWER must be the oracle's.
        reset_fast_path()
        try:
            snap = _degenerate_snapshot(seed=27)
            snap.healthy[::13] = False
            rng = np.random.default_rng(28)
            grid = ScenarioGrid(
                cpu_request_milli=rng.integers(100, 2000, 9),
                mem_request_bytes=rng.integers(64, 2048, 9) * (1 << 20),
                replicas=rng.integers(1, 500, 9),
            )
            g = grouped_for_dispatch(snap)
            assert g is not None
            totals, sched, kernel = _sweep_auto_grouped(g, grid, mode=mode)
            assert kernel in (
                "pallas_i32_rcp_fused_grouped",
                "pallas_i32_fused_grouped",
                "xla_int64_grouped",
            )
            expected = _oracle_fits(snap, grid, mode).sum(axis=1)
            np.testing.assert_array_equal(totals, expected)
        finally:
            reset_fast_path()

    def test_masked_auto_matches_unmasked_minus_masked_nodes(self):
        reset_fast_path()
        try:
            snap = _degenerate_snapshot(seed=29)
            mask = np.random.default_rng(30).random(snap.n_nodes) < 0.5
            grid = random_scenario_grid(6, seed=31)
            totals, _, _ = sweep_snapshot_auto(
                snap, grid, mode="strict", node_mask=mask
            )
            expected = _oracle_fits(
                snap, grid, "strict", node_mask=mask
            ).sum(axis=1)
            np.testing.assert_array_equal(totals, expected)
        finally:
            reset_fast_path()


class TestGroupedDevcache:
    def test_grouped_form_caches_and_invalidates(self):
        cache = devcache.DeviceCache()
        snap = _degenerate_snapshot(seed=33)
        g = snap.grouped()
        first = cache.grouped_arrays(g)
        again = cache.grouped_arrays(g)
        assert first is again
        stats = cache.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1
        # padded to the GROUP bucket, not the node bucket
        assert first[0].shape[0] == devcache.node_bucket(g.n_groups)
        assert first[0].shape[0] < snap.n_nodes
        # counts ride in slot 8; padding is zero-count
        counts = np.asarray(first[7])
        assert int(counts.sum()) == snap.n_nodes
        cache.invalidate(snap)
        assert cache.stats()["entries"] == 0

    def test_grouped_sweep_populates_grouped_form(self):
        before = devcache.CACHE.stats()["misses"]
        snap = _degenerate_snapshot(seed=34)
        grid = random_scenario_grid(5, seed=35)
        sweep_grouped_bucketed(
            snap.grouped(), grid.cpu_request_milli,
            grid.mem_request_bytes, grid.replicas,
        )
        assert devcache.CACHE.stats()["misses"] > before


class TestGroupedExplain:
    @pytest.mark.parametrize("mode", ("reference", "strict"))
    def test_grouped_explain_matches_per_node(self, mode, monkeypatch):
        snap = _degenerate_snapshot(seed=37)
        snap.healthy[::7] = False
        grid = random_scenario_grid(6, seed=38)
        assert grouped_for_dispatch(snap) is not None
        got = explain_snapshot(snap, grid, mode=mode)
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        want = explain_snapshot(snap, grid, mode=mode)
        for f in ("fits", "binding", "cpu_fit", "mem_fit", "slots"):
            np.testing.assert_array_equal(
                getattr(got, f), getattr(want, f), err_msg=f
            )

    def test_grouped_explain_masked_matches_per_node(self, monkeypatch):
        snap = _degenerate_snapshot(seed=39)
        mask = np.random.default_rng(40).random(snap.n_nodes) < 0.7
        grid = random_scenario_grid(4, seed=41)
        got = explain_snapshot(snap, grid, node_mask=mask)
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        want = explain_snapshot(snap, grid, node_mask=mask)
        for f in ("fits", "binding"):
            np.testing.assert_array_equal(
                getattr(got, f), getattr(want, f), err_msg=f
            )
        # expansion preserved node granularity: every node has a code
        assert got.binding.shape == (grid.size, snap.n_nodes)


class TestGroupedGspmd:
    def test_gspmd_grouped_matches_unsharded(self, monkeypatch):
        from kubernetesclustercapacity_tpu.parallel import make_mesh
        from kubernetesclustercapacity_tpu.parallel.sweep import (
            sweep_gspmd_grouped,
        )

        snap = _degenerate_snapshot(seed=43, n=4099)  # forces padding
        grid = random_scenario_grid(13, seed=44)
        g = snap.grouped()
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        base = sweep_snapshot(snap, grid)
        monkeypatch.delenv("KCCAP_GROUPING")
        for sp, np_ in ((2, 4), (1, 8)):
            plan = make_mesh(sp, np_)
            totals, sched = sweep_gspmd_grouped(
                plan, g, grid.cpu_request_milli, grid.mem_request_bytes,
                grid.replicas,
            )
            np.testing.assert_array_equal(totals, base[0])
            np.testing.assert_array_equal(sched, base[1])

    def test_gspmd_grouped_masked(self, monkeypatch):
        from kubernetesclustercapacity_tpu.parallel import make_mesh
        from kubernetesclustercapacity_tpu.parallel.sweep import (
            sweep_gspmd_grouped,
        )

        snap = _degenerate_snapshot(seed=45)
        mask = np.random.default_rng(46).random(snap.n_nodes) < 0.5
        grid = random_scenario_grid(9, seed=47)
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        base = sweep_snapshot(snap, grid, mode="strict", node_mask=mask)
        monkeypatch.delenv("KCCAP_GROUPING")
        plan = make_mesh(4, 2)
        totals, _ = sweep_gspmd_grouped(
            plan, snap.grouped(), grid.cpu_request_milli,
            grid.mem_request_bytes, grid.replicas, mode="strict",
            node_mask=mask,
        )
        np.testing.assert_array_equal(totals, base[0])


class TestGroupMetricsPublish:
    def test_gauges_update_on_publish(self):
        from kubernetesclustercapacity_tpu.snapshot import (
            publish_group_metrics,
        )
        from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY

        snap = _degenerate_snapshot(seed=49)
        publish_group_metrics(snap)
        snap_reg = REGISTRY.snapshot()
        g = snap.grouped()
        assert snap_reg["kccap_group_count"]["values"][""] == g.n_groups
        ratio = snap_reg["kccap_compression_ratio"]["values"][""]
        assert ratio == round(g.compression_ratio, 4)

    def test_grouping_off_means_no_update(self, monkeypatch):
        from kubernetesclustercapacity_tpu.snapshot import (
            publish_group_metrics,
        )
        from kubernetesclustercapacity_tpu.telemetry.metrics import REGISTRY

        a = _degenerate_snapshot(seed=50)
        b = _degenerate_snapshot(seed=51, shapes=7)
        publish_group_metrics(a)
        before = REGISTRY.snapshot()["kccap_group_count"]["values"]
        monkeypatch.setenv("KCCAP_GROUPING", "0")
        publish_group_metrics(b)
        after = REGISTRY.snapshot()["kccap_group_count"]["values"]
        assert before == after


class TestTimelineShapeJoins:
    def _timeline(self):
        from kubernetesclustercapacity_tpu.timeline import CapacityTimeline
        from kubernetesclustercapacity_tpu.timeline.watchlist import (
            parse_watchlist,
        )

        specs = parse_watchlist(
            {
                "watches": [
                    {
                        "name": "web",
                        "pod": {
                            "cpuRequests": "500m",
                            "memRequests": "1gb",
                        },
                    }
                ]
            }
        )
        return CapacityTimeline(specs, depth=4)

    @staticmethod
    def _with_rows(snap, idx, names):
        kw = {
            f: np.asarray(getattr(snap, f))[idx]
            for f in (
                "alloc_cpu_milli", "alloc_mem_bytes", "alloc_pods",
                "used_cpu_req_milli", "used_cpu_lim_milli",
                "used_mem_req_bytes", "used_mem_lim_bytes",
                "pods_count", "healthy",
            )
        }
        return dataclasses.replace(snap, names=names, **kw)

    def test_node_joining_existing_group_is_attributed(self):
        tl = self._timeline()
        base = synthetic_snapshot(24, seed=42)
        tl.observe(base, 1)
        twin = self._with_rows(
            base, list(range(24)) + [0], base.names + ["node-twin"]
        )
        tl.observe(twin, 2)
        (delta,) = tl.deltas()
        assert delta["nodes_added"] == ["node-twin"]
        (join,) = delta["shape_joins"]
        assert join["node"] == "node-twin"
        assert len(join["shape"]) == 8
        summary = delta["watches"]["web"]["summary"]
        assert f"+1 shape {join['shape']}" in summary

    def test_zero_contribution_join_is_not_silent(self):
        # The joined shape fits ZERO replicas of the watch — without the
        # shape clause this transition would read as a no-op.
        tl = self._timeline()
        base = synthetic_snapshot(24, seed=42)
        base.alloc_cpu_milli[0] = 1  # 500m never fits: cpu_fit = 0
        base.used_cpu_req_milli[0] = 0
        tl.observe(base, 1)
        twin = self._with_rows(
            base, list(range(24)) + [0], base.names + ["node-twin"]
        )
        tl.observe(twin, 2)
        (delta,) = tl.deltas()
        w = delta["watches"]["web"]
        assert w["after"] == w["before"]  # capacity did not move...
        assert "+1 shape " in w["summary"]  # ...but the census did
        assert "node-twin" in w["summary"]

    def test_new_shape_is_not_a_join(self):
        tl = self._timeline()
        base = synthetic_snapshot(24, seed=42)
        tl.observe(base, 1)
        grown = self._with_rows(
            base, list(range(24)) + [0], base.names + ["node-new"]
        )
        grown.alloc_cpu_milli[-1] = 123_456  # a shape nobody had
        tl.observe(grown, 2)
        (delta,) = tl.deltas()
        assert delta["shape_joins"] == []
        assert "+1 shape" not in delta["watches"]["web"]["summary"]


class TestSyntheticShapes:
    def test_shapes_param_bounds_distinct_rows(self):
        snap = synthetic_snapshot(5000, seed=1, shapes=13)
        assert snap.grouped().n_groups <= 13
        assert snap.n_nodes == 5000
        assert len(set(snap.names)) == 5000  # names stay unique

    def test_default_remains_heterogeneous(self):
        snap = synthetic_snapshot(300, seed=1)
        assert snap.grouped().n_groups > 250

    def test_fast_kernel_eligibility_preserved(self):
        from kubernetesclustercapacity_tpu.ops.pallas_fit import (
            fast_sweep_eligible,
        )

        snap = synthetic_snapshot(2000, seed=2, shapes=19)
        g = snap.grouped()
        grid = ScenarioGrid(
            cpu_request_milli=np.array([250]),
            mem_request_bytes=np.array([512 << 20]),
            replicas=np.array([1]),
        )
        assert fast_sweep_eligible(
            g.alloc_cpu_milli, g.alloc_mem_bytes, g.alloc_pods,
            g.used_cpu_req_milli, g.used_mem_req_bytes, g.pods_count,
            grid.cpu_request_milli, grid.mem_request_bytes,
            counts=g.count,
        )


def test_grouping_env_default_is_enabled():
    assert os.environ.get("KCCAP_GROUPING") is None
    assert snapshot_mod.grouping_enabled()
