"""Serving plane: pub-sub fan-out, admission control, graceful drain.

The acceptance bar (ISSUE 10): every staged replica generation is
digest-verified before it is served (a garbled stream resyncs, never
mis-applies); admission sheds by deadline slack BEFORE touching the
device with exact counters under a 16-thread hammer; SIGTERM and the
``drain_server`` op finish in-flight work and emit a durable drain
record; and the protocol handshake degrades cleanly in both
old-client/new-server directions.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.fixtures import load_fixture
from kubernetesclustercapacity_tpu.resilience import (
    DeadlineExpired,
    Deadline,
    DrainingError,
    NotLeaderError,
    OverloadedError,
    TokenBucket,
)
from kubernetesclustercapacity_tpu.service import protocol
from kubernetesclustercapacity_tpu.service.client import CapacityClient
from kubernetesclustercapacity_tpu.service.plane import (
    PLANE_PROTOCOL_VERSION,
    AdmissionController,
    PlanePublisher,
    PlaneSubscriber,
)
from kubernetesclustercapacity_tpu.service.server import CapacityServer
from kubernetesclustercapacity_tpu.snapshot import (
    snapshot_from_fixture,
    synthetic_snapshot,
)
from kubernetesclustercapacity_tpu.telemetry.metrics import MetricsRegistry
from kubernetesclustercapacity_tpu.testing_faults import FaultPlan, FaultProxy

KIND = "tests/fixtures/kind-3node.json"


def _wait_for(predicate, timeout_s=8.0, interval_s=0.01, what="condition"):
    """Poll until ``predicate()`` is truthy (deterministic completion
    signal; the asserts themselves never sleep)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def _mutate(snap, seed):
    """A derived generation: deterministic usage churn (same shape/
    names, different fit answers)."""
    import dataclasses

    rng = np.random.default_rng(seed)
    used = snap.used_cpu_req_milli + rng.integers(
        0, 200, size=snap.n_nodes, dtype=np.int64
    )
    return dataclasses.replace(snap, used_cpu_req_milli=used)


@pytest.fixture()
def kind_snap():
    return snapshot_from_fixture(load_fixture(KIND), semantics="reference")


# ---------------------------------------------------------------------------
# TokenBucket
# ---------------------------------------------------------------------------
class TestTokenBucket:
    def test_refill_matches_numpy_oracle(self):
        """The lazy-refill arithmetic vs an independent recurrence over
        the same (fake) clock timeline: token level and grant verdicts
        identical at every step."""
        rate, cap = 7.0, 12.0
        now = [100.0]
        bucket = TokenBucket(rate, cap, clock=lambda: now[0])
        rng = np.random.default_rng(42)
        dts = rng.uniform(0.0, 0.6, size=400)
        # Oracle: level_i = min(cap, level_{i-1} + dt_i*rate); grant
        # iff level >= 1, then level -= 1 (float64, same arithmetic).
        level = np.float64(cap)
        for dt in dts:
            now[0] += float(dt)
            level = np.minimum(np.float64(cap), level + np.float64(dt) * rate)
            want_grant = bool(level >= 1.0)
            got_grant = bucket.try_acquire()
            assert got_grant == want_grant
            if want_grant:
                level = level - np.float64(1.0)
            assert bucket.available() == pytest.approx(float(level), abs=1e-9)

    def test_starts_full_and_caps(self):
        now = [0.0]
        b = TokenBucket(1.0, 3.0, clock=lambda: now[0])
        assert [b.try_acquire() for _ in range(4)] == [True] * 3 + [False]
        now[0] += 1000.0  # refill far past capacity: clamps to 3
        assert b.available() == pytest.approx(3.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            TokenBucket(0.0)
        with pytest.raises(ValueError):
            TokenBucket(1.0, 0.5)
        with pytest.raises(ValueError):
            TokenBucket(1.0).try_acquire(0)


# ---------------------------------------------------------------------------
# AdmissionController
# ---------------------------------------------------------------------------
class TestAdmissionController:
    def test_deadline_expired_sheds_before_any_gate(self):
        """An already-expired deadline sheds with DeadlineExpired and
        debits NOTHING: no token leaves the bucket, no queue entry."""
        now = [0.0]
        adm = AdmissionController(
            max_concurrent=4, rps=10.0, burst=10.0, clock=lambda: now[0]
        )
        before = adm._bucket.available()
        with pytest.raises(DeadlineExpired):
            adm.admit("sweep", Deadline.after(-0.5))
        assert adm._bucket.available() == before
        assert adm._shed == {"deadline": 1}
        assert adm._admitted == 0

    def test_min_slack_sheds_not_yet_expired_deadlines(self):
        adm = AdmissionController(max_concurrent=4, min_slack_s=5.0)
        with pytest.raises(DeadlineExpired):
            adm.admit("sweep", Deadline.after(1.0))  # alive, but < slack

    def test_rps_shed_is_overloaded(self):
        now = [0.0]
        adm = AdmissionController(rps=2.0, burst=2.0, clock=lambda: now[0])
        adm.admit("sweep")()
        adm.admit("sweep")()
        with pytest.raises(OverloadedError):
            adm.admit("sweep")
        now[0] += 0.5  # one token refills
        adm.admit("sweep")()
        assert adm._shed == {"rps": 1}

    def test_concurrency_queue_then_shed(self):
        adm = AdmissionController(max_concurrent=1, max_queue_wait_s=0.05)
        release = adm.admit("sweep")
        with pytest.raises(OverloadedError):
            adm.admit("sweep")  # queue wait lapses, sheds
        release()
        adm.admit("sweep")()  # slot free again
        assert adm._shed == {"concurrency": 1}
        assert adm._queue_depth == 0

    def test_shed_counter_exact_under_16_thread_hammer(self):
        """Every governed request counts exactly once: admitted + shed
        == issued, across 16 threads × 50 requests with a contended
        2-slot gate and zero queue patience."""
        adm = AdmissionController(max_concurrent=2, max_queue_wait_s=0.0)
        threads, per = 16, 50
        outcomes = {"ok": 0, "shed": 0}
        lock = threading.Lock()

        def worker():
            ok = shed = 0
            for _ in range(per):
                try:
                    release = adm.admit("sweep")
                except OverloadedError:
                    shed += 1
                    continue
                release()
                ok += 1
            with lock:
                outcomes["ok"] += ok
                outcomes["shed"] += shed

        ts = [threading.Thread(target=worker) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert outcomes["ok"] + outcomes["shed"] == threads * per
        assert adm._admitted == outcomes["ok"]
        assert sum(adm._shed.values()) == outcomes["shed"]
        assert adm._queue_depth == 0

    def test_server_sheds_expired_deadline_without_touching_device(
        self, kind_snap
    ):
        """Wired into a server: a sweep whose deadline is spent at
        admission is refused before grid parsing, batching, or any
        kernel dispatch — the sweep-kernel histogram never moves."""
        registry = MetricsRegistry()
        adm = AdmissionController(max_concurrent=4, registry=registry)
        srv = CapacityServer(
            kind_snap, port=0, registry=registry, admission=adm
        )
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                # Sanity: a live deadline dispatches fine.
                ok = c.sweep(
                    cpu_request_milli=[100], mem_request_bytes=[10 ** 8],
                    replicas=[1], deadline_s=30.0,
                )
                assert ok["totals"]
                kernel_hist = registry.histogram(
                    "kccap_sweep_kernel_seconds",
                    "", ("kernel",),
                )
                before = sum(
                    child.count for _, child in kernel_hist._items()
                )
                msg = {
                    "op": "sweep",
                    "cpu_request_milli": [100],
                    "mem_request_bytes": [10 ** 8],
                    "replicas": [1],
                    "deadline": time.time() - 5.0,  # spent before arrival
                }
                # Issue the raw expired-deadline frame (the client's own
                # budget check would otherwise shed it locally).
                sock = socket.create_connection(srv.address)
                try:
                    protocol.send_msg(sock, msg)
                    resp = protocol.recv_msg(sock)
                finally:
                    sock.close()
                assert resp["ok"] is False
                assert "DeadlineExpired" in resp["error"]
                after = sum(
                    child.count for _, child in kernel_hist._items()
                )
                assert after == before  # the device was never touched
                shed = registry.counter(
                    "kccap_admission_shed_total", "", ("op", "reason")
                )
                assert shed.labels(op="sweep", reason="deadline").value == 1
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# Publisher / subscriber
# ---------------------------------------------------------------------------
class TestPlaneStream:
    def test_checkpoint_then_diffs_digest_verified(self, kind_snap):
        pub = PlanePublisher()
        leader = CapacityServer(kind_snap, port=0, plane=pub)
        leader.start()
        replica = CapacityServer(kind_snap, port=0)
        replica.start()
        sub = PlaneSubscriber(pub.address, replica, stale_after_s=30.0)
        try:
            _wait_for(lambda: sub.applied_generation >= 1,
                      what="initial checkpoint")
            snap2 = _mutate(kind_snap, 1)
            leader.replace_snapshot(snap2)
            snap3 = _mutate(snap2, 2)
            leader.replace_snapshot(snap3)
            _wait_for(lambda: sub.applied_generation >= 3, what="diffs")
            # The replica serves the leader's generation numbering and
            # the EXACT arrays (digest-proven, asserted via the fit op).
            assert replica.generation == leader.generation == 3
            with CapacityClient(*replica.address) as c:
                fits = c.fit(cpuRequests="100m", memRequests="100mb")["fits"]
                assert c.last_generation == 3
            with CapacityClient(*leader.address) as c:
                assert fits == c.fit(
                    cpuRequests="100m", memRequests="100mb"
                )["fits"]
            st = sub.stats()
            assert st["applied"] >= 3 and st["errors"] == 0
            assert pub.stats()["subscribers"] == 1
        finally:
            sub.stop()
            pub.close()
            leader.shutdown()
            replica.shutdown()

    def test_resume_ack_when_replica_already_current(self, kind_snap):
        """A reconnecting replica whose (generation, digest) matches the
        leader's current state gets a resume ack, not a redundant
        checkpoint transfer."""
        pub = PlanePublisher()
        leader = CapacityServer(kind_snap, port=0, plane=pub)
        replica = CapacityServer(kind_snap, port=0)
        sub = PlaneSubscriber(pub.address, replica, stale_after_s=30.0)
        try:
            _wait_for(lambda: sub.applied_generation >= 1, what="checkpoint")
            applied_before = sub.stats()["applied"]
            # Cut the link: the subscriber reconnects and resumes.
            with sub._lock:
                sock = sub._sock
            sock.close()
            _wait_for(
                lambda: sub.stats()["resyncs"] >= 1, what="reconnect"
            )
            # Publish one more generation: stream is live again.
            leader.replace_snapshot(_mutate(kind_snap, 5))
            _wait_for(lambda: sub.applied_generation >= 2, what="post-resume")
            # The reconnect staged nothing redundant (resume, not
            # checkpoint re-apply): exactly one more applied generation.
            assert sub.stats()["applied"] == applied_before + 1
        finally:
            sub.stop()
            pub.close()
            leader.shutdown()
            replica.shutdown()

    @pytest.mark.parametrize("fault", ["garbage", "drop_pre", "partial"])
    def test_garbled_stream_resyncs_never_misapplies(self, kind_snap, fault):
        """Corrupting / gapping / tearing plane frames NEVER yields a
        wrong staged snapshot: the replica resyncs through a fresh
        checkpoint and converges to the leader's exact state."""
        pub = PlanePublisher()
        leader = CapacityServer(kind_snap, port=0, plane=pub)
        leader.start()
        replica = CapacityServer(kind_snap, port=0)
        replica.start()
        # Fault every 3rd server frame, forever-ish.
        plan = FaultPlan([None, None, fault] * 30)
        proxy = FaultProxy(pub.address, plan, stream=True).start()
        sub = PlaneSubscriber(
            proxy.address, replica, stale_after_s=30.0, seed=7,
            reconnect_base_s=0.01, reconnect_max_s=0.05,
        )
        try:
            # Attach first: the faults must hit live STREAM frames, not
            # be skipped by a single post-hoc checkpoint.
            _wait_for(lambda: sub.applied_generation >= 1,
                      what="initial checkpoint")
            snap = kind_snap
            for i in range(8):
                snap = _mutate(snap, i)
                leader.replace_snapshot(snap)
                time.sleep(0.02)  # let frames traverse the faulty link
            target = leader.generation
            _wait_for(
                lambda: sub.applied_generation == target,
                timeout_s=15.0,
                what=f"convergence under {fault}",
            )
            # Convergence is digest-proven inside the subscriber; cross
            # check the served arrays anyway.
            with CapacityClient(*replica.address) as cr, CapacityClient(
                *leader.address
            ) as cl:
                want = cl.fit(cpuRequests="250m", memRequests="200mb")
                got = cr.fit(cpuRequests="250m", memRequests="200mb")
                assert got["fits"] == want["fits"]
                assert cr.last_generation == target
            assert plan.injected[fault] >= 1  # the fault actually fired
        finally:
            sub.stop()
            proxy.stop()
            pub.close()
            leader.shutdown()
            replica.shutdown()

    def test_slow_subscriber_ejected_not_wedged(self):
        """A subscriber that never drains its socket is ejected once its
        queue fills — the leader's publish path never blocks on it.
        Frames are sized past the kernel socket buffer (every row of a
        4k-node snapshot churns per generation) so the writer thread
        genuinely wedges on the unread peer instead of parking 40 tiny
        frames in the OS buffer."""
        import dataclasses

        registry = MetricsRegistry()
        pub = PlanePublisher(max_queue=2, registry=registry)
        snap = synthetic_snapshot(4096, seed=2)
        leader = CapacityServer(snap, port=0, plane=pub)
        try:
            # A raw socket that hellos and then never reads (tiny
            # receive buffer, so backpressure hits the writer fast).
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            sock.connect(pub.address)
            protocol.send_msg(
                sock, {"plane": PLANE_PROTOCOL_VERSION, "generation": 0,
                       "digest": ""}
            )
            _wait_for(
                lambda: pub.stats()["subscribers"] == 1, what="attach"
            )
            # Cap the publisher-side send buffer too: the kernel
            # autotunes SNDBUF into the megabytes, which would absorb
            # many ~180 KB diff frames before sendall ever blocks.
            with pub._lock:
                pub._subs[0].sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDBUF, 4096
                )
            t0 = time.monotonic()
            for i in range(12):  # far past max_queue; must not block
                snap = dataclasses.replace(
                    snap,
                    used_cpu_req_milli=snap.used_cpu_req_milli + 1 + i,
                )
                leader.replace_snapshot(snap)
            assert time.monotonic() - t0 < 10.0  # publish never blocked
            _wait_for(
                lambda: pub.stats()["ejected"] == 1, what="ejection"
            )
            assert pub.stats()["subscribers"] == 0
            sock.close()
        finally:
            pub.close()
            leader.shutdown()

    def test_staleness_is_clock_bounded(self, kind_snap):
        """With an injectable clock: the replica flips stale exactly
        when the silent interval passes stale_after_s — deterministic,
        no real sleeps."""
        now = [1000.0]
        pub = PlanePublisher(heartbeat_s=3600.0)  # no heartbeats
        leader = CapacityServer(kind_snap, port=0, plane=pub)
        replica = CapacityServer(kind_snap, port=0)
        sub = PlaneSubscriber(
            pub.address, replica, stale_after_s=5.0, clock=lambda: now[0]
        )
        try:
            _wait_for(lambda: sub.applied_generation >= 1, what="checkpoint")
            assert not sub.stale
            now[0] += 4.9
            assert not sub.stale
            now[0] += 0.2  # crosses the bound
            assert sub.stale
            assert sub.stats()["stale"] is True
            # Any frame (a published generation) resets the bound.
            leader.replace_snapshot(_mutate(kind_snap, 9))
            _wait_for(lambda: sub.applied_generation >= 2, what="frame")
            assert not sub.stale
        finally:
            sub.stop()
            pub.close()
            leader.shutdown()
            replica.shutdown()

    def test_replica_refuses_mutations_with_not_leader(self, kind_snap):
        pub = PlanePublisher()
        leader = CapacityServer(kind_snap, port=0, plane=pub)
        leader.start()
        replica = CapacityServer(kind_snap, port=0)
        replica.start()
        sub = PlaneSubscriber(pub.address, replica, stale_after_s=30.0)
        try:
            _wait_for(lambda: sub.applied_generation >= 1, what="checkpoint")
            with CapacityClient(*replica.address) as c:
                with pytest.raises(NotLeaderError):
                    c.update([{"kind": "node", "type": "DELETED",
                               "name": "x"}])
                info = c.info()
                assert info["capabilities"]["plane"] is True
                assert c.plane_status()["role"] == "replica"
        finally:
            sub.stop()
            pub.close()
            leader.shutdown()
            replica.shutdown()

    def test_generation_never_regresses_on_replica(self, kind_snap):
        replica = CapacityServer(kind_snap, port=0)
        replica.replace_snapshot(_mutate(kind_snap, 1), generation=7)
        assert replica.generation == 7
        with pytest.raises(ValueError, match="regress"):
            replica.replace_snapshot(_mutate(kind_snap, 2), generation=3)
        replica.replace_snapshot(_mutate(kind_snap, 2), generation=7)
        assert replica.generation == 7
        replica.shutdown()

    def test_publisher_rejects_bad_hello(self, kind_snap):
        pub = PlanePublisher(token="sekrit")
        try:
            # Wrong version.
            s = socket.create_connection(pub.address)
            protocol.send_msg(s, {"plane": 999})
            assert protocol.recv_msg(s)["kind"] == "reject"
            s.close()
            # Missing token.
            s = socket.create_connection(pub.address)
            protocol.send_msg(s, {"plane": PLANE_PROTOCOL_VERSION})
            assert protocol.recv_msg(s)["kind"] == "reject"
            s.close()
        finally:
            pub.close()


# ---------------------------------------------------------------------------
# Graceful drain
# ---------------------------------------------------------------------------
class TestGracefulDrain:
    def test_drain_finishes_inflight_then_refuses(self, kind_snap, tmp_path):
        """In-flight compute finishes during a drain; new compute and
        mutations are refused with the retryable-elsewhere code; the
        drain record is durable in the audit log."""
        from kubernetesclustercapacity_tpu.audit import AuditLog

        audit = AuditLog(str(tmp_path / "audit"))
        srv = CapacityServer(
            kind_snap, port=0, audit_log=audit, batch_window_ms=0.0
        )
        srv.start()
        release = threading.Event()
        entered = threading.Event()
        orig = srv._op_sweep

        def slow_sweep(msg, snap, implicit_mask=None, fixture=None):
            entered.set()
            release.wait(5.0)
            return orig(msg, snap, implicit_mask, fixture)

        srv._op_sweep = slow_sweep
        results = {}

        def call_sweep():
            with CapacityClient(*srv.address) as c:
                results["sweep"] = c.sweep(
                    cpu_request_milli=[100], mem_request_bytes=[10 ** 8],
                    replicas=[1],
                )

        t = threading.Thread(target=call_sweep)
        t.start()
        entered.wait(5.0)

        done = {}

        def drain():
            done["record"] = srv.begin_drain(timeout_s=10.0, reason="test")

        dt = threading.Thread(target=drain)
        dt.start()
        time.sleep(0.05)  # drain is now waiting on the in-flight sweep
        assert srv.draining
        release.set()
        dt.join(10.0)
        t.join(10.0)
        record = done["record"]
        assert record["drained"] is True
        assert record["inflight_at_start"] == 1
        assert results["sweep"]["totals"]  # the in-flight answer landed
        # New compute AND mutations refuse with the draining code.
        with CapacityClient(*srv.address) as c:
            with pytest.raises(DrainingError):
                c.sweep(cpu_request_milli=[100],
                        mem_request_bytes=[10 ** 8], replicas=[1])
            with pytest.raises(DrainingError):
                c.update([])
            assert c.ping() == "pong"  # diagnostics keep answering
            assert c.info()["draining"] is True
            # Idempotent: the second drain returns the first record.
            again = c.drain_server()
            assert again["already"] is True
            assert again["waited_s"] == record["waited_s"]
        # The durable drain record rode the audit log.
        audit.close()
        srv.shutdown()
        from kubernetesclustercapacity_tpu.audit import AuditReader

        recs = AuditReader.load(str(tmp_path / "audit")).records
        drains = [r for r in recs if r.get("kind") == "drain"]
        assert len(drains) == 1 and drains[0]["reason"] == "test"

    def test_drain_timeout_reports_undrained(self, kind_snap):
        srv = CapacityServer(kind_snap, port=0, batch_window_ms=0.0)
        srv.start()
        release = threading.Event()
        orig = srv._op_sweep

        def wedged_sweep(msg, snap, implicit_mask=None, fixture=None):
            release.wait(10.0)
            return orig(msg, snap, implicit_mask, fixture)

        srv._op_sweep = wedged_sweep
        t = threading.Thread(
            target=lambda: CapacityClient(*srv.address).sweep(
                cpu_request_milli=[100], mem_request_bytes=[10 ** 8],
                replicas=[1],
            )
        )
        t.start()
        _wait_for(lambda: srv._active_gated == 1, what="in-flight sweep")
        record = srv.begin_drain(timeout_s=0.1, reason="wedged")
        assert record["drained"] is False
        assert record["inflight_remaining"] == 1
        release.set()
        t.join(10.0)
        srv.shutdown()

    def test_concurrent_drains_one_record(self, kind_snap):
        srv = CapacityServer(kind_snap, port=0)
        srv.start()
        out = []
        ts = [
            threading.Thread(
                target=lambda: out.append(srv.begin_drain(timeout_s=2.0))
            )
            for _ in range(8)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(out) == 8
        firsts = [r for r in out if not r.get("already")]
        assert len(firsts) == 1  # exactly one drain actually ran
        srv.shutdown()

    def test_sigterm_routes_through_graceful_drain(self, kind_snap, tmp_path):
        """kccap-server under SIGTERM: drains, prints the drain record
        line, exits 0 — in-flight requests are not dropped abruptly."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "kubernetesclustercapacity_tpu.service.server",
                "-snapshot", KIND, "-port", "0",
                "-drain-timeout-s", "5",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        try:
            # The server prints its bound address once serving.
            addr = None
            deadline = time.monotonic() + 120
            lines = []
            while time.monotonic() < deadline:
                line = proc.stderr.readline()
                if not line:
                    break
                lines.append(line)
                if line.startswith("serving "):
                    hp = line.rsplit(" on ", 1)[1].strip()
                    host, _, port = hp.rpartition(":")
                    addr = (host, int(port))
                    break
            assert addr is not None, f"no serving line in {lines!r}"
            with CapacityClient(*addr) as c:
                assert c.ping() == "pong"
                proc.send_signal(signal.SIGTERM)
                # Diagnostics still answer while draining.
                _wait_for(
                    lambda: c.info().get("draining"),
                    timeout_s=10.0, what="draining flag",
                )
            proc.wait(timeout=30)
            rest = proc.stderr.read()
            stderr = "".join(lines) + rest
            assert proc.returncode == 0
            assert "draining on signal" in stderr
            assert "drain complete" in stderr
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Client close(): idempotent + thread-safe
# ---------------------------------------------------------------------------
class TestClientClose:
    def test_close_idempotent_and_concurrent_with_inflight(self, kind_snap):
        """close() may race in-flight calls and other closers: every
        combination must end with a closed client and NO exception
        families beyond the expected transport errors on the in-flight
        calls themselves."""
        srv = CapacityServer(kind_snap, port=0)
        srv.start()
        try:
            for _ in range(10):
                c = CapacityClient(*srv.address, timeout_s=5.0)
                c.ping()
                unexpected = []
                stop = threading.Event()

                def caller():
                    while not stop.is_set():
                        try:
                            c.ping()
                        except Exception as e:  # noqa: BLE001 - classified below
                            # A call racing close() may see a torn
                            # transport (fine) — anything else is a bug.
                            from kubernetesclustercapacity_tpu.service.protocol import (  # noqa: E501
                                ProtocolError,
                            )

                            if not isinstance(e, (OSError, ProtocolError)):
                                unexpected.append(e)
                            return

                def closer():
                    c.close()

                threads = [threading.Thread(target=caller) for _ in range(3)]
                threads += [threading.Thread(target=closer) for _ in range(4)]
                for t in threads:
                    t.start()
                stop.set()
                for t in threads:
                    t.join(10.0)
                assert not unexpected
                c.close()  # idempotent: a second (Nth) close is a no-op
                c.close()
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# Protocol handshake / capability degradation
# ---------------------------------------------------------------------------
class _OldServer:
    """A pre-plane server: framed JSON, ping/info/sweep only, NO
    capabilities key, NO envelope generation, unknown ops error — the
    regression double for 'new client against old server'."""

    def __init__(self, snap):
        self._snap = snap
        self._listener = socket.create_server(("127.0.0.1", 0))
        self._listener.settimeout(0.2)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @property
    def address(self):
        return self._listener.getsockname()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        try:
            while True:
                msg = protocol.recv_msg(conn)
                if msg is None:
                    return
                op = msg.get("op")
                if op == "ping":
                    reply = {"ok": True, "result": "pong"}
                elif op == "info":
                    reply = {
                        "ok": True,
                        "result": {
                            "nodes": self._snap.n_nodes,
                            "semantics": self._snap.semantics,
                        },
                    }
                else:
                    reply = {"ok": False,
                             "error": f"ValueError: unknown op {op!r}"}
                protocol.send_msg(conn, reply)
        except (OSError, protocol.ProtocolError):
            return
        finally:
            conn.close()

    def close(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class TestHandshake:
    def test_old_client_against_new_server(self, kind_snap):
        """A pre-plane client (raw frames, no envelope awareness) gets
        byte-compatible behavior from a new server: extra envelope keys
        are additive, pinned info keys unchanged."""
        srv = CapacityServer(kind_snap, port=0)
        srv.start()
        try:
            sock = socket.create_connection(srv.address)
            protocol.send_msg(sock, {"op": "ping"})
            resp = protocol.recv_msg(sock)
            assert resp["ok"] is True and resp["result"] == "pong"
            assert isinstance(resp.get("generation"), int)  # additive only
            protocol.send_msg(sock, {"op": "info"})
            info = protocol.recv_msg(sock)["result"]
            # The pre-plane key set is intact...
            for key in ("nodes", "semantics", "healthy_nodes",
                        "extended_resources", "resilience"):
                assert key in info
            # ...and the handshake advertises the new families.
            assert info["capabilities"] == {
                "protocol": 2, "plane": False, "admission": False,
                "drain": True, "tenancy": False,
            }
            sock.close()
        finally:
            srv.shutdown()

    def test_new_client_against_old_server_degrades_cleanly(self, kind_snap):
        from kubernetesclustercapacity_tpu.service.replicaset import (
            ReplicaSet,
            ReplicaSetError,
        )

        old = _OldServer(kind_snap)
        try:
            with CapacityClient(*old.address) as c:
                assert c.ping() == "pong"
                assert c.capabilities() == {}  # absent, not an error
                assert c.last_generation is None  # never stamped
            rs = ReplicaSet([old.address])
            try:
                assert rs.ping() == "pong"
                rs.probe()
                # Feature gate: a clean local refusal, not an unknown-op
                # server error.
                assert not rs.capability("drain")
                with pytest.raises(ReplicaSetError, match="drain"):
                    rs.drain_server()
                # Monotonicity degrades to best-effort: no watermark.
                assert rs.watermark == 0
            finally:
                rs.close()
        finally:
            old.close()

    def test_unknown_op_is_clean_error_both_ways(self, kind_snap):
        srv = CapacityServer(kind_snap, port=0)
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                with pytest.raises(RuntimeError, match="unknown op"):
                    c.call("plane_subscribe_v99")
        finally:
            srv.shutdown()
