"""MicroBatcher mechanics + micro-batched sweep bit-exactness.

The batcher's contract: a batch of one IS the solo path; concurrent
same-key submits share exactly one dispatch; deadline-starved requests
bypass; a failing dispatch fails every member; the metrics add up.  The
bit-exactness half drives the server-style concatenate-and-scatter
dispatch over random grids in both semantics modes and compares every
scattered slice against its solo sweep and the sequential oracle.
"""

import threading
import time

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.resilience import Deadline
from kubernetesclustercapacity_tpu.scenario import (
    ScenarioGrid,
    random_scenario_grid,
)
from kubernetesclustercapacity_tpu.service.batching import MicroBatcher
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot


def _echo_dispatch(calls):
    def dispatch(key, items):
        calls.append((key, list(items)))
        return [(key, item, len(items)) for item in items]

    return dispatch


class TestMechanics:
    def test_single_submit_is_batch_of_one(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=0.005)
        out = b.submit("k", "item")
        assert out == ("k", "item", 1)
        assert len(calls) == 1
        st = b.stats
        assert st["dispatches"] == 1
        assert st["solo_requests"] == 1
        assert st["batched_requests"] == 0
        assert st["mean_batch_size"] == 1.0

    def test_concurrent_submits_share_one_dispatch(self):
        calls = []
        release = threading.Event()

        def slow_dispatch(key, items):
            calls.append(list(items))
            return [len(items)] * len(items)

        b = MicroBatcher(slow_dispatch, window_s=0.25, max_batch=8)
        results = [None] * 6
        barrier = threading.Barrier(6)

        def worker(i):
            barrier.wait()
            results[i] = b.submit("k", i)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        release.set()
        # All six rode one dispatch (the barrier puts them well inside
        # the 250 ms window) and each got the shared batch size back.
        assert len(calls) == 1 and len(calls[0]) == 6
        assert results == [6] * 6
        st = b.stats
        assert st["dispatches"] == 1
        assert st["batched_requests"] == 6
        assert st["mean_batch_size"] == 6.0

    def test_full_batch_dispatches_before_window(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=5.0, max_batch=2)
        t0 = time.perf_counter()
        results = [None, None]

        def worker(i):
            results[i] = b.submit("k", i)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in (0, 1)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        # max_batch=2 reached -> the leader dispatched long before the
        # 5 s window expired.
        assert time.perf_counter() - t0 < 2.0
        assert sorted(r[1] for r in results) == [0, 1]

    def test_deadline_inside_window_bypasses(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=0.2)
        out = b.submit("k", "hurried", deadline=Deadline.after(0.05))
        assert out == ("k", "hurried", 1)
        st = b.stats
        assert st["deadline_bypass"] == 1
        assert st["dispatches"] == 1

    def test_roomy_deadline_still_batches(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=0.01)
        b.submit("k", "calm", deadline=Deadline.after(30.0))
        assert b.stats["deadline_bypass"] == 0

    def test_dispatch_error_fails_every_member(self):
        def boom(key, items):
            raise RuntimeError("kernel exploded")

        b = MicroBatcher(boom, window_s=0.1, max_batch=4)
        errors = []
        barrier = threading.Barrier(3)

        def worker():
            barrier.wait()
            try:
                b.submit("k", "x")
            except RuntimeError as e:
                errors.append(str(e))

        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(errors) == 3
        assert all("kernel exploded" in e for e in errors)

    def test_result_count_mismatch_is_an_error(self):
        b = MicroBatcher(lambda k, items: [], window_s=0.005)
        with pytest.raises(RuntimeError, match="0 results"):
            b.submit("k", "x")

    def test_distinct_keys_never_share(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=0.2, max_batch=8)
        results = {}
        barrier = threading.Barrier(4)

        def worker(key, i):
            barrier.wait()
            results[(key, i)] = b.submit(key, i)

        threads = [
            threading.Thread(target=worker, args=(key, i))
            for i, key in enumerate(["a", "a", "b", "b"])
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(calls) == 2
        assert all(key == k for (key, _), (k, _, _) in results.items())

    def test_invalid_construction(self):
        with pytest.raises(ValueError):
            MicroBatcher(lambda k, i: [], window_s=0.0)
        with pytest.raises(ValueError):
            MicroBatcher(lambda k, i: [], window_s=0.01, max_batch=0)

    def test_follower_with_tight_deadline_bypasses(self):
        """Regression: the bypass decision is PER MEMBER against the
        batch it would actually join.  A follower whose deadline cannot
        survive the leader's remaining window must go solo — consulting
        only the leader's deadline (or comparing followers against the
        FULL window) strands the follower behind a wait it cannot
        afford.  The injected clock makes the remaining-window budget
        deterministic."""
        fake = [100.0]
        calls = []
        leader_started = threading.Event()

        def dispatch(key, items):
            calls.append(list(items))
            return [len(items)] * len(items)

        b = MicroBatcher(
            dispatch, window_s=0.5, max_batch=8, clock=lambda: fake[0]
        )
        results = {}

        def leader():
            leader_started.set()
            results["leader"] = b.submit("k", "L")

        t = threading.Thread(target=leader)
        t.start()
        leader_started.wait(5)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and b.stats["dispatches"] == 0:
            # The leader's batch is open (pending) once submit enters its
            # window wait; poll until the follower can observably join.
            with b._lock:
                if "k" in b._pending:
                    break
            time.sleep(0.001)
        # Injected clock: 0.2s of the 0.5s window "elapsed" -> remaining
        # budget 0.3s.  This follower's 0.1s deadline is tighter: solo.
        fake[0] = 100.2
        out = b.submit("k", "tight", deadline=Deadline.after(0.1))
        assert out == 1  # dispatched alone, immediately
        assert b.stats["deadline_bypass"] == 1
        t.join(10)
        assert results["leader"] == 1  # the leader's batch never saw it
        assert sorted(len(c) for c in calls) == [1, 1]

    def test_follower_joins_when_remaining_window_fits(self):
        """The flip side: a follower whose deadline is tighter than the
        FULL window but roomier than the REMAINING window must still
        join (bypassing it would spend a dispatch the deadline never
        required)."""
        fake = [100.0]
        calls = []
        leader_started = threading.Event()

        def dispatch(key, items):
            calls.append(list(items))
            return [len(items)] * len(items)

        b = MicroBatcher(
            dispatch, window_s=0.5, max_batch=2, clock=lambda: fake[0]
        )
        results = {}

        def leader():
            leader_started.set()
            results["leader"] = b.submit("k", "L")

        t = threading.Thread(target=leader)
        t.start()
        leader_started.wait(5)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with b._lock:
                if "k" in b._pending:
                    break
            time.sleep(0.001)
        # 0.45s of the 0.5s window "elapsed" -> remaining budget 0.05s.
        # A 0.2s deadline would have bypassed against the full 0.5s
        # window; against the honest remainder it joins (and max_batch=2
        # dispatches the pair immediately).
        fake[0] = 100.45
        out = b.submit("k", "roomy", deadline=Deadline.after(0.2))
        t.join(10)
        assert out == 2 and results["leader"] == 2  # one shared dispatch
        assert b.stats["deadline_bypass"] == 0
        assert [len(c) for c in calls] == [2]


def _sweep_dispatch(snap, mode):
    """The server-style combined dispatch: concatenate scenario rows,
    one sweep, scatter slices."""
    from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot

    def dispatch(_key, grids):
        combined = ScenarioGrid(
            cpu_request_milli=np.concatenate(
                [g.cpu_request_milli for g in grids]
            ),
            mem_request_bytes=np.concatenate(
                [g.mem_request_bytes for g in grids]
            ),
            replicas=np.concatenate([g.replicas for g in grids]),
        )
        totals, sched = sweep_snapshot(snap, combined, mode=mode)
        out, off = [], 0
        for g in grids:
            out.append((totals[off:off + g.size], sched[off:off + g.size]))
            off += g.size
        return out

    return dispatch


class TestBatchedBitExactness:
    @pytest.mark.parametrize("mode", ["reference", "strict"])
    def test_batched_equals_solo_and_oracle(self, mode):
        from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot

        snap = synthetic_snapshot(90, seed=1, alloc_pods=5)
        snap.pods_count[::4] = 9  # Q1 overwrite -> negative fits
        snap.healthy[::3] = False
        grids = [random_scenario_grid(1 + i % 7, seed=i) for i in range(12)]
        b = MicroBatcher(
            _sweep_dispatch(snap, mode), window_s=0.1, max_batch=16
        )
        results = [None] * len(grids)
        barrier = threading.Barrier(len(grids))

        def worker(i):
            barrier.wait()
            results[i] = b.submit("gen-1", grids[i])

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(len(grids))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert b.stats["batched_requests"] > 0  # it really batched
        for i, g in enumerate(grids):
            totals, sched = results[i]
            solo_t, solo_s = sweep_snapshot(snap, g, mode=mode)
            np.testing.assert_array_equal(totals, solo_t)
            np.testing.assert_array_equal(sched, solo_s)
            # And element-for-element against the sequential oracle.
            for j in range(g.size):
                fits = fit_arrays_python(
                    snap.alloc_cpu_milli, snap.alloc_mem_bytes,
                    snap.alloc_pods, snap.used_cpu_req_milli,
                    snap.used_mem_req_bytes, snap.pods_count,
                    int(g.cpu_request_milli[j]),
                    int(g.mem_request_bytes[j]),
                    mode=mode, healthy=snap.healthy,
                )
                assert int(totals[j]) == int(
                    np.asarray(fits, dtype=np.int64).sum()
                )

    def test_batching_single_request_equals_solo_path(self):
        from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot

        snap = synthetic_snapshot(50, seed=2)
        grid = random_scenario_grid(8, seed=3)
        b = MicroBatcher(
            _sweep_dispatch(snap, "reference"), window_s=0.002
        )
        totals, sched = b.submit("gen-1", grid)
        solo_t, solo_s = sweep_snapshot(snap, grid)
        np.testing.assert_array_equal(totals, solo_t)
        np.testing.assert_array_equal(sched, solo_s)
        assert b.stats["solo_requests"] == 1


class TestMixedTenantBitExactness:
    """The multi-tenancy fold: concurrent DIFFERENT tenants' same-key
    sweeps share one padded dispatch, split per tenant on return — and
    every tenant's slice is bit-exact vs its solo sweep (the combined
    dispatch is index-scattered and never reads the label)."""

    @pytest.mark.parametrize("mode", ["reference", "strict"])
    def test_mixed_tenant_batch_equals_solo(self, mode):
        from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot

        snap = synthetic_snapshot(70, seed=21, alloc_pods=6)
        snap.healthy[::5] = False
        grids = [random_scenario_grid(1 + i % 5, seed=100 + i)
                 for i in range(10)]
        tenants = [f"tenant-{i % 4}" for i in range(10)]  # 4 identities
        b = MicroBatcher(
            _sweep_dispatch(snap, mode), window_s=0.1, max_batch=16
        )
        results = [None] * len(grids)
        barrier = threading.Barrier(len(grids))

        def worker(i):
            barrier.wait()
            results[i] = b.submit("gen-1", grids[i], tenant=tenants[i])

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(len(grids))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert b.stats["batched_requests"] > 0  # tenants really folded
        # The tenant-spread histogram saw a genuinely multi-tenant batch.
        tenants_hist = b.registry.snapshot()["kccap_batch_tenants"]
        assert tenants_hist["values"][""]["sum"] >= 4
        for i, g in enumerate(grids):
            totals, sched = results[i]
            solo_t, solo_s = sweep_snapshot(snap, g, mode=mode)
            np.testing.assert_array_equal(totals, solo_t)
            np.testing.assert_array_equal(sched, solo_s)

    def test_tenant_spread_histogram_counts_distinct_tenants(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=0.2, max_batch=4)
        barrier = threading.Barrier(4)
        names = ["a", "a", "b", "c"]

        def worker(i):
            barrier.wait()
            b.submit("k", i, tenant=names[i])

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(calls) == 1  # one shared dispatch
        hist = b.registry.snapshot()["kccap_batch_tenants"]["values"][""]
        assert hist["count"] == 1 and hist["sum"] == 3.0  # {a, b, c}

    def test_tenantless_submit_observes_one(self):
        calls = []
        b = MicroBatcher(_echo_dispatch(calls), window_s=0.005)
        b.submit("k", "x")  # no tenant: the pre-tenancy path
        hist = b.registry.snapshot()["kccap_batch_tenants"]["values"][""]
        assert hist["count"] == 1 and hist["sum"] == 1.0
