"""Table-driven codec tests covering SURVEY.md §2.2 edge cases."""

import pytest

from kubernetesclustercapacity_tpu.utils.quantity import (
    Quantity,
    QuantityParseError,
    byte_size,
    cpu_to_milli_reference,
    cpu_to_milli_strict,
    mem_to_bytes_strict,
    parse_quantity,
    to_bytes_reference,
    to_megabytes,
)

KIB = 1024
MIB = 1024 * KIB
GIB = 1024 * MIB
TIB = 1024 * GIB


class TestCpuToMilliReference:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("100m", 100),  # m-suffix: value as-is
            ("250m", 250),
            ("0m", 0),
            ("2", 2000),  # cores -> x1000
            ("4", 4000),
            ("0", 0),
            ("+3", 3000),  # Go Atoi accepts a leading sign
            ("1000m", 1000),
        ],
    )
    def test_valid(self, s, expected):
        assert cpu_to_milli_reference(s) == expected

    @pytest.mark.parametrize(
        "s",
        ["0.5", "2.5", "", "m", "100Mi", "1e2", "abc", " 2", "2 ", "1_0", "٢"],
    )
    def test_parse_failure_yields_zero(self, s):
        # ClusterCapacity.go:314-317 — failure prints an error and returns 0.
        assert cpu_to_milli_reference(s) == 0

    def test_negative_wraps_like_go_uint64(self):
        # uint64(int(-5 * 1000)) in Go.
        assert cpu_to_milli_reference("-5") == 2**64 - 5000
        assert cpu_to_milli_reference("-5m") == 2**64 - 5

    def test_double_m_suffix(self):
        # "5mm" -> strip one m -> "5m" -> Atoi fails -> 0.
        assert cpu_to_milli_reference("5mm") == 0

    def test_int64_range_error_yields_zero(self):
        # Go strconv.Atoi errors outside int64 range -> reference returns 0.
        assert cpu_to_milli_reference("9" * 30) == 0
        assert cpu_to_milli_reference(str(2**63)) == 0
        assert cpu_to_milli_reference(str(2**63 - 1)) == ((2**63 - 1) * 1000) % 2**64


class TestToBytesReference:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("100mb", 100 * MIB),  # ALL prefixes base-2: MB == MiB
            ("100MB", 100 * MIB),
            ("100M", 100 * MIB),
            ("100MiB", 100 * MIB),
            ("100Mi", 100 * MIB),  # "MI" accepted
            ("1k", KIB),
            ("3500Ki", 3500 * KIB),  # kubelet-style allocatable
            ("1KB", KIB),
            ("2g", 2 * GIB),
            ("2GB", 2 * GIB),
            ("2GiB", 2 * GIB),
            ("1T", TIB),
            ("1TiB", TIB),
            ("5B", 5),
            ("  250mb  ", 250 * MIB),  # whitespace trimmed
            ("0.5M", MIB // 2),  # float value allowed
            ("1.5K", 1536),
        ],
    )
    def test_valid(self, s, expected):
        assert to_bytes_reference(s) == expected

    @pytest.mark.parametrize(
        "s",
        [
            "16Gi",  # "GI" missing from suffix table (bytes.go:91-104)
            "1Ti",  # "TI" missing too
            "1073741824",  # no letter suffix -> error
            "0Ki",  # value <= 0 -> error
            "-5M",
            "",
            "MB",
            "1XB",
            "nanB",
            "infM",
            "2 GB",  # internal space: Go ParseFloat("2 ") errors
            "9" * 400 + "M",  # float64 overflow -> Go ErrRange -> error
        ],
    )
    def test_invalid(self, s):
        with pytest.raises(QuantityParseError):
            to_bytes_reference(s)

    def test_truncation_toward_zero(self):
        # int64(value * mult) truncates: 0.0009765625KiB < 1 byte.
        assert to_bytes_reference("1.0009765625K") == 1025
        assert to_bytes_reference("0.3B") == 0


class TestByteSizeFormat:
    @pytest.mark.parametrize(
        "n,expected",
        [
            (0, "0"),
            (5, "5B"),
            (KIB, "1K"),
            (int(100.5 * MIB), "100.5M"),
            (GIB, "1G"),
            (int(1.5 * TIB), "1.5T"),
            (1536, "1.5K"),
        ],
    )
    def test_format(self, n, expected):
        assert byte_size(n) == expected

    def test_to_megabytes(self):
        assert to_megabytes("2048K") == 2
        assert to_megabytes("1536K") == 1  # floor


class TestStrictQuantity:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("1", 1),
            ("100", 100),
            ("1Ki", 1024),
            ("16Gi", 16 * GIB),  # strict parser fixes the GI gap
            ("1Ti", TIB),
            ("1M", 10**6),  # decimal SI is base-10 in strict mode
            ("1k", 1000),
            ("1e3", 1000),
            ("1E3", 1000),
            ("12e-1", 2),  # 1.2 rounds UP to 2
            ("100m", 1),  # Value() rounds up: 0.1 -> 1
            ("1500m", 2),
            ("0.5", 1),
            ("1.5Gi", 1610612736),
            ("0", 0),
            ("-1500m", -2),  # away from zero, like upstream Value()
            ("-100m", -1),  # upstream MustParse("-100m").Value() == -1
        ],
    )
    def test_value(self, s, expected):
        assert parse_quantity(s).value() == expected

    @pytest.mark.parametrize(
        "s,expected",
        [
            ("100m", 100),
            ("0.5", 500),
            ("2", 2000),
            ("1u", 1),  # 1e-6 cores -> ceil to 1 milli
            ("250m", 250),
        ],
    )
    def test_milli_value(self, s, expected):
        assert parse_quantity(s).milli_value() == expected

    @pytest.mark.parametrize(
        "s",
        [
            "",
            "K",
            "1K",
            "1KB",
            "1MiB",
            "abc",
            "1.2.3",
            ".",
            "1e",
            "1ee3",
            "--1",
            " 1Gi",  # upstream rejects surrounding whitespace
            "1Gi ",
            "5e\u0663",  # Unicode exponent digits: ASCII-only upstream
        ],
    )
    def test_invalid(self, s):
        with pytest.raises(QuantityParseError):
            parse_quantity(s)

    @pytest.mark.parametrize(
        "s,expected",
        [
            # Upstream caps what int64 cannot hold instead of erroring.
            ("16E", (1 << 63) - 1),
            ("1e19", (1 << 63) - 1),
            ("-16E", -(1 << 63)),
            # Unbounded exponents clamp (never materialize 10**exp): huge
            # caps, tiny rounds away from zero.
            ("1e1000000000", (1 << 63) - 1),
            ("1e-1000000000", 1),
            ("-1e-1000000000", -1),
            ("0e1000000000", 0),
        ],
    )
    def test_int64_capping(self, s, expected):
        assert parse_quantity(s).value() == expected

    def test_milli_value_caps(self):
        assert parse_quantity("10E").milli_value() == (1 << 63) - 1

    def test_exact_decimal_no_float_drift(self):
        # 0.1 is exactly 1/10, so 0.1 * 3 * 10 == 3 exactly.
        q = parse_quantity("0.1")
        assert (q.amount * 30).denominator == 1
        assert isinstance(q, Quantity)

    def test_helpers(self):
        assert cpu_to_milli_strict("0.5") == 500
        assert mem_to_bytes_strict("16Gi") == 16 * GIB


class TestAsciiOnlyParseFloat:
    """Go strconv.ParseFloat is ASCII-only: Unicode decimal digits that
    Python's float() would transform (e.g. Arabic-Indic "١٥") must be a
    parse error, exactly as the Go reference and the native codec treat
    them."""

    def test_unicode_digits_rejected(self):
        import pytest as _pytest

        from kubernetesclustercapacity_tpu.utils.quantity import (
            QuantityParseError,
            to_bytes_reference,
        )

        assert float("١٥") == 15.0  # the trap this guards
        with _pytest.raises(QuantityParseError):
            to_bytes_reference("١٥MB")


class TestGoQuote:
    """``go_quote`` must match Go ``strconv.Quote`` byte-for-byte — the
    ``%q`` inside the fatal replicas line's ``strconv.Atoi`` error
    (``ClusterCapacity.go:81``).  Expected strings below are Go outputs."""

    CASES = [
        ("ten", '"ten"'),
        ("a\nb", '"a\\nb"'),
        ("tab\there", '"tab\\there"'),
        ("\x01", '"\\x01"'),
        ("\x7f", '"\\x7f"'),
        ('say "hi"', '"say \\"hi\\""'),
        ("back\\slash", '"back\\\\slash"'),
        ("héllo", '"héllo"'),
        (" ", '"\\u00a0"'),  # NBSP: Zs, non-print under Go IsPrint
        (" ", '"\\u202f"'),  # narrow NBSP
        ("﻿", '"\\ufeff"'),  # BOM: Cf
        ("\U0001f600", '"\U0001f600"'),  # emoji: So, printable
        (" spaced ", '" spaced "'),  # ASCII space stays literal
    ]

    def test_known_go_outputs(self):
        from kubernetesclustercapacity_tpu.utils.quantity import go_quote

        for raw, want in self.CASES:
            assert go_quote(raw) == want, repr(raw)

    def test_pep383_surrogate_prints_original_byte(self):
        """argv bytes that are invalid UTF-8 reach Python as surrogate
        escapes; Go quotes the raw byte as \\xhh."""
        from kubernetesclustercapacity_tpu.utils.quantity import go_quote

        raw = b"ab\xffc".decode("utf-8", "surrogateescape")
        assert go_quote(raw) == '"ab\\xffc"'

    def test_atoi_error_embeds_quoting(self):
        from kubernetesclustercapacity_tpu.utils.quantity import (
            go_atoi_error,
        )

        assert go_atoi_error("\x01en") == (
            'strconv.Atoi: parsing "\\x01en": invalid syntax'
        )
