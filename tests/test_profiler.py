"""Continuous profiler: collapsed-stack folding joined to the live
phase table, the analysis helpers ``kccap -profile`` and bench share,
and the ``KCCAP_PROFILER=0`` hatch's zero-thread / zero-registry pin."""

import threading

import pytest

from kubernetesclustercapacity_tpu.telemetry import phases
from kubernetesclustercapacity_tpu.telemetry import profiler as prof_mod
from kubernetesclustercapacity_tpu.telemetry.profiler import (
    SamplingProfiler,
    attribution_counts,
    dominant_phase,
    phase_counts,
    render_collapsed,
    top_frame,
)

# A hand-built collapsed profile: three attributed stacks (two op=sweep
# with tenant, one without tenant) and one unattributed bench loop.
COLLAPSED = (
    "op=sweep;tenant=acme;phase=device_exec;server:dispatch;"
    "fit:sweep_auto 6\n"
    "op=sweep;tenant=acme;phase=serialize;server:_respond;"
    "report:render 3\n"
    "op=sweep;phase=fetch;server:dispatch;fit:_materialize 1\n"
    "bench:_arrival_loop;threading:wait 10\n"
)


class TestCollapsedAnalysis:
    def test_phase_counts_includes_the_unattributed_bucket(self):
        assert phase_counts(COLLAPSED) == {
            "device_exec": 6,
            "serialize": 3,
            "fetch": 1,
            "-": 10,
        }

    def test_attribution_counts_by_op_and_tenant(self):
        assert attribution_counts(COLLAPSED, "op") == {
            "sweep": 10,
            "-": 10,
        }
        assert attribution_counts(COLLAPSED, "tenant") == {
            "acme": 9,
            "-": 11,
        }

    def test_dominant_phase_is_over_attributed_samples_only(self):
        phase, share = dominant_phase(COLLAPSED)
        assert phase == "device_exec"
        assert share == pytest.approx(0.6)

    def test_dominant_phase_none_when_nothing_attributed(self):
        assert dominant_phase("a:b;c:d 5\n") == (None, 0.0)

    def test_top_frame_skips_attribution_prefixes(self):
        # The heaviest REAL leaf overall is the bench wait loop...
        assert top_frame(COLLAPSED) == "threading:wait"
        # ...but restricted to a phase, the prefixes never win even
        # though they lead every attributed stack.
        assert top_frame(COLLAPSED, phase="device_exec") == "fit:sweep_auto"
        assert top_frame(COLLAPSED, phase="serialize") == "report:render"

    def test_render_collapsed_sorts_heaviest_first(self):
        text = render_collapsed({"a:b": 1, "c:d": 9, "e:f": 5})
        assert text.splitlines() == ["c:d 9", "e:f 5", "a:b 1"]
        assert render_collapsed({}) == ""

    def test_comment_and_blank_lines_are_ignored(self):
        text = "# profiler header\n\na:b;c:d 4\n"
        assert phase_counts(text) == {"-": 4}


class TestLiveAttribution:
    def test_phase_block_publishes_and_clears(self):
        clk = phases.PhaseClock()
        ident = threading.get_ident()
        with clk.phase("serialize"):
            assert phases.live_snapshot()[ident] == (
                None, None, "serialize",
            )
        assert ident not in phases.live_snapshot()

    def test_live_block_publishes_without_recording(self):
        clk = phases.PhaseClock()
        ident = threading.get_ident()
        with clk.live("device_exec"):
            assert phases.live_snapshot()[ident] == (
                None, None, "device_exec",
            )
        assert ident not in phases.live_snapshot()
        # Attribution only: the accounting stays with the site's own
        # record() calls.
        assert clk.items() == []
        assert clk.counts() == {}

    def test_live_nests_and_restores_the_outer_phase(self):
        clk = phases.PhaseClock()
        ident = threading.get_ident()
        with clk.phase("devcache"):
            with clk.live("fetch"):
                assert phases.live_snapshot()[ident][2] == "fetch"
            assert phases.live_snapshot()[ident][2] == "devcache"
        assert ident not in phases.live_snapshot()

    def test_live_preserves_op_and_tenant(self):
        ident = threading.get_ident()
        phases.live_set(op="sweep", tenant="acme")
        try:
            clk = phases.PhaseClock()
            with clk.live("device_exec"):
                assert phases.live_snapshot()[ident] == (
                    "sweep", "acme", "device_exec",
                )
            assert phases.live_snapshot()[ident] == ("sweep", "acme", None)
        finally:
            phases.live_clear()
        assert ident not in phases.live_snapshot()

    def test_live_rejects_unknown_phase(self):
        clk = phases.PhaseClock()
        with pytest.raises(phases.PhaseError):
            with clk.live("warp_drive"):
                pass

    def test_null_clock_live_is_the_shared_noop(self):
        # Same singleton context as phase(): zero allocations per call.
        ctx = phases.NULL_CLOCK.live("device_exec")
        assert ctx is phases.NULL_CLOCK.phase("serialize")
        ident = threading.get_ident()
        with phases.NULL_CLOCK.live("device_exec"):
            assert ident not in phases.live_snapshot()


class TestSampler:
    def _worker(self, ready, release):
        phases.live_set(op="sweep", tenant="acme")
        clk = phases.PhaseClock()
        try:
            with clk.live("device_exec"):
                ready.set()
                release.wait(10)
        finally:
            phases.live_clear()

    def test_sample_once_joins_the_live_table(self):
        prof = SamplingProfiler(hz=50)
        ready, release = threading.Event(), threading.Event()
        t = threading.Thread(target=self._worker, args=(ready, release))
        t.start()
        try:
            assert ready.wait(10)
            prof.sample_once()
        finally:
            release.set()
            t.join(10)
        samples, counts = prof.snapshot()
        assert samples == 1
        text = render_collapsed(counts)
        assert phase_counts(text).get("device_exec", 0) >= 1
        assert attribution_counts(text, "op").get("sweep", 0) >= 1
        assert attribution_counts(text, "tenant").get("acme", 0) >= 1

    def test_snapshot_accumulates_and_stats_report(self):
        # The sampler folds every thread EXCEPT its caller, so park a
        # helper for it to see.
        prof = SamplingProfiler(hz=7)
        release = threading.Event()
        t = threading.Thread(target=release.wait, args=(10,))
        t.start()
        try:
            prof.sample_once()
            prof.sample_once()
        finally:
            release.set()
            t.join(10)
        samples, counts = prof.snapshot()
        assert samples == 2
        assert counts  # the parked helper's stack at minimum
        for stack in counts:
            for frame in stack.split(";"):
                assert " " not in frame
        st = prof.stats()
        assert st["hz"] == 7.0
        assert st["samples"] == 2
        assert st["running"] is False


class TestProfilerOff:
    def test_dedicated_hatch_disables(self, monkeypatch):
        monkeypatch.setenv("KCCAP_PROFILER", "0")
        assert not prof_mod.enabled()

    def test_telemetry_off_disables_too(self, monkeypatch):
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        assert not prof_mod.enabled()

    def test_start_spawns_no_thread_and_touches_no_registry(
        self, monkeypatch
    ):
        monkeypatch.setenv("KCCAP_PROFILER", "0")
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            REGISTRY,
        )

        def boom(*a, **kw):
            raise AssertionError("registry touched with profiler off")

        monkeypatch.setattr(REGISTRY, "counter", boom)
        prof = SamplingProfiler()
        assert prof.start() is prof
        assert not prof.running()
        ctype, body = prof.debug_handler("seconds=0")
        assert body.startswith(b"# profiler disabled")

    def test_singleton_start_returns_none(self, monkeypatch):
        monkeypatch.setenv("KCCAP_PROFILER", "0")
        assert prof_mod.start_profiler() is None

    def test_env_hz_parsing(self, monkeypatch):
        monkeypatch.setenv("KCCAP_PROFILE_HZ", "53")
        assert SamplingProfiler().hz == 53.0
        monkeypatch.setenv("KCCAP_PROFILE_HZ", "not-a-number")
        assert SamplingProfiler().hz == float(prof_mod.DEFAULT_HZ)
        monkeypatch.setenv("KCCAP_PROFILE_HZ", "-3")
        assert SamplingProfiler().hz == float(prof_mod.DEFAULT_HZ)
