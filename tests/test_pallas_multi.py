"""R-dim Pallas fast-path tests (interpret mode on CPU; TPU via bench)."""

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.ops.fit import sweep_grid_multi
from kubernetesclustercapacity_tpu.ops.pallas_multi import (
    fast_multi_eligible,
    multi_row_scales,
    rcp_multi_eligible,
    sweep_multi_auto,
    sweep_pallas_multi,
)
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot

GIB = 1 << 30


def _workload(n, s, seed, *, gpu_zeros=True):
    """Config-4-shaped inputs: cpu, memory, ephemeral-storage, GPU rows."""
    rng = np.random.default_rng(seed)
    snap = synthetic_snapshot(n, seed=seed)
    alloc_rn = np.stack(
        [
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            rng.integers(50, 500, n) * GIB,
            rng.integers(0, 9, n),
        ]
    )
    used_rn = np.stack(
        [
            snap.used_cpu_req_milli,
            snap.used_mem_req_bytes,
            rng.integers(0, 50, n) * GIB,
            np.zeros(n, dtype=np.int64),
        ]
    )
    reqs_sr = np.stack(
        [
            rng.integers(1, 10, s) * 100,
            rng.integers(1, 16, s) * (64 << 20),
            rng.integers(1, 20, s) * GIB,
            rng.integers(0, 3, s) if gpu_zeros else rng.integers(1, 3, s),
        ],
        axis=1,
    ).astype(np.int64)
    reps = rng.integers(1, 500, s).astype(np.int64)
    return snap, alloc_rn, used_rn, reqs_sr, reps


class TestEligibility:
    def test_config4_workload_eligible_and_rcp(self):
        snap, alloc_rn, used_rn, reqs_sr, _ = _workload(500, 32, seed=1)
        scales, ok = fast_multi_eligible(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count, reqs_sr
        )
        assert ok
        # cpu milli scale 1; memory + ephemeral rows pick a power of 1024.
        assert scales[0] == 1 and scales[1] >= 1024 and scales[2] >= 1024
        assert scales[3] == 1
        assert rcp_multi_eligible(alloc_rn, used_rn, reqs_sr, scales)

    def test_unquantized_row_ineligible(self):
        snap, alloc_rn, used_rn, reqs_sr, _ = _workload(50, 8, seed=2)
        alloc_rn[1, 0] += 1  # de-quantize one memory cell, i32-overflow row
        assert multi_row_scales(alloc_rn, used_rn, reqs_sr) is None

    def test_negative_request_ineligible(self):
        snap, alloc_rn, used_rn, reqs_sr, _ = _workload(50, 8, seed=3)
        reqs_sr[0, 3] = -1
        assert multi_row_scales(alloc_rn, used_rn, reqs_sr) is None

    def test_sum_overflow_ineligible(self):
        snap, alloc_rn, used_rn, reqs_sr, _ = _workload(50, 8, seed=4)
        alloc_rn[0, :] = 2_000_000_000
        reqs_sr[:, 0] = 1
        scales, ok = fast_multi_eligible(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count, reqs_sr
        )
        assert scales is not None and not ok


class TestParity:
    @pytest.mark.parametrize("n,s", [(100, 10), (2048, 256), (2049, 257)])
    @pytest.mark.parametrize("mode", ["strict", "reference"])
    def test_matches_exact_kernel(self, n, s, mode):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(n, s, seed=n + s)
        snap.healthy[::5] = False
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode=mode,
        )
        mask = snap.healthy if mode == "strict" else None
        scales = multi_row_scales(alloc_rn, used_rn, reqs_sr)
        totals, sched = sweep_pallas_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            reqs_sr, reps, scales, mode=mode, node_mask=mask,
            interpret=True,
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))
        np.testing.assert_array_equal(sched, np.asarray(exact[1]))

    def test_all_zero_request_scenario(self):
        # A scenario consuming nothing: every row inactive -> the epilogue
        # bounds the int-max sentinel identically on both paths.
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(150, 8, seed=7)
        reqs_sr[3, :] = 0
        for mode in ("strict", "reference"):
            exact = sweep_grid_multi(
                alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
                snap.healthy, reqs_sr, reps, mode=mode,
            )
            scales = multi_row_scales(alloc_rn, used_rn, reqs_sr)
            mask = snap.healthy if mode == "strict" else None
            totals, _ = sweep_pallas_multi(
                alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
                reqs_sr, reps, scales, mode=mode, node_mask=mask,
                interpret=True,
            )
            np.testing.assert_array_equal(totals, np.asarray(exact[0]))

    @pytest.mark.parametrize("seed", [0, 1])
    def test_forced_rcp_matches_forced_divide(self, seed):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(777, 64, seed=seed)
        scales = multi_row_scales(alloc_rn, used_rn, reqs_sr)
        assert rcp_multi_eligible(alloc_rn, used_rn, reqs_sr, scales)
        args = (
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            reqs_sr, reps, scales,
        )
        t_div, _ = sweep_pallas_multi(
            *args, mode="strict", node_mask=snap.healthy,
            use_rcp=False, interpret=True,
        )
        t_rcp, _ = sweep_pallas_multi(
            *args, mode="strict", node_mask=snap.healthy,
            use_rcp=True, interpret=True,
        )
        np.testing.assert_array_equal(t_rcp, t_div)

    def test_two_resource_agrees_with_2d_kernel_surface(self):
        # R=2 multi fast path must agree with the exact 2-resource sweep
        # in strict mode (same semantics there; reference differs by the
        # uint64-CPU quirk, which multi does not carry).
        from kubernetesclustercapacity_tpu.ops.fit import sweep_grid

        snap = synthetic_snapshot(300, seed=9)
        rng = np.random.default_rng(10)
        s = 16
        cpu = rng.integers(1, 10, s) * 100
        mem = rng.integers(1, 16, s) * (64 << 20)
        reps = np.ones(s, dtype=np.int64)
        reqs_sr = np.stack([cpu, mem], axis=1).astype(np.int64)
        alloc_rn = np.stack([snap.alloc_cpu_milli, snap.alloc_mem_bytes])
        used_rn = np.stack(
            [snap.used_cpu_req_milli, snap.used_mem_req_bytes]
        )
        exact2, _ = sweep_grid(
            snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
            snap.used_cpu_req_milli, snap.used_mem_req_bytes,
            snap.pods_count, snap.healthy, cpu, mem, reps, mode="strict",
        )
        scales = multi_row_scales(alloc_rn, used_rn, reqs_sr)
        totals, _ = sweep_pallas_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            reqs_sr, reps, scales, mode="strict", node_mask=snap.healthy,
            interpret=True,
        )
        np.testing.assert_array_equal(totals, np.asarray(exact2))


class TestAuto:
    def test_auto_fused_when_eligible(self):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(400, 24, seed=11)
        totals, sched, kernel = sweep_multi_auto(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", interpret=True,
        )
        assert kernel.startswith("pallas_multi_")
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict",
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))

    def test_auto_shared_mask_fused(self):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(400, 24, seed=12)
        rng = np.random.default_rng(13)
        mask = rng.random(400) < 0.6
        totals, _, kernel = sweep_multi_auto(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", node_masks=mask,
            interpret=True,
        )
        assert kernel.startswith("pallas_multi_")
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", node_masks=mask,
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))

    def test_auto_per_scenario_masks_fall_back(self):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(100, 8, seed=14)
        rng = np.random.default_rng(15)
        masks = rng.random((8, 100)) < 0.6
        totals, _, kernel = sweep_multi_auto(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", node_masks=masks,
            interpret=True,
        )
        assert kernel == "xla_int64_multi"
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", node_masks=masks,
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))

    def test_auto_max_per_node_falls_back(self):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(100, 8, seed=16)
        _, _, kernel = sweep_multi_auto(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", max_per_node=2,
            interpret=True,
        )
        assert kernel == "xla_int64_multi"

    def test_auto_ineligible_falls_back(self):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(100, 8, seed=17)
        alloc_rn[1, 0] += 1  # de-quantize -> row can't fit int32
        totals, _, kernel = sweep_multi_auto(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", interpret=True,
        )
        assert kernel == "xla_int64_multi"
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict",
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))

    def test_force_exact(self):
        snap, alloc_rn, used_rn, reqs_sr, reps = _workload(100, 8, seed=18)
        _, _, kernel = sweep_multi_auto(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict", force_exact=True,
            interpret=True,
        )
        assert kernel == "xla_int64_multi"
