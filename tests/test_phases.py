"""Per-request latency decomposition: PhaseClock semantics, the
16-thread concurrency hammer, the server wiring (histograms, flight
records, trace child spans), reconciliation of sum-of-phases against
end-to-end latency, the injected-slow-phase attribution, the bench
breakdown helper, and the KCCAP_TELEMETRY=0 zero-allocation pin."""

import json
import os
import pathlib
import sys
import threading
import time

import numpy as np
import pytest

import kubernetesclustercapacity_tpu as kcc
from kubernetesclustercapacity_tpu.service.server import CapacityServer
from kubernetesclustercapacity_tpu.telemetry import phases
from kubernetesclustercapacity_tpu.telemetry.metrics import MetricsRegistry

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


def _sweep_msg(n=4):
    mib = 1024 * 1024
    return {
        "op": "sweep",
        "cpu_request_milli": [100 * (i + 1) for i in range(n)],
        "mem_request_bytes": [mib * (i + 1) for i in range(n)],
        "replicas": [1] * n,
    }


class TestPhaseClock:
    def test_record_accumulates_in_vocabulary_order(self):
        clk = phases.PhaseClock()
        clk.record("fetch", 0.002)
        clk.record("queue_wait", 0.001)
        clk.record("fetch", 0.003)
        assert clk.items() == [("queue_wait", 0.001), ("fetch", 0.005)]
        assert clk.counts() == {"queue_wait": 1, "fetch": 2}
        assert clk.to_ms() == {"queue_wait": 1.0, "fetch": 5.0}
        assert clk.total_s() == pytest.approx(0.006)

    def test_unknown_phase_rejected(self):
        clk = phases.PhaseClock()
        with pytest.raises(phases.PhaseError):
            clk.record("warp_drive", 0.1)
        with pytest.raises(phases.PhaseError):
            clk.move("fetch", "warp_drive")

    def test_move_reattributes_everything(self):
        clk = phases.PhaseClock()
        clk.record("device_exec", 0.01)
        clk.record("fetch", 0.02)
        clk.record("compile", 0.5)
        clk.move("device_exec", "compile")
        clk.move("fetch", "compile")
        assert clk.items() == [("compile", pytest.approx(0.53))]
        assert clk.counts() == {"compile": 3}
        clk.move("device_exec", "compile")  # absent src: no-op
        assert clk.counts() == {"compile": 3}

    def test_phase_context_manager_times_the_block(self):
        clk = phases.PhaseClock()
        with clk.phase("serialize"):
            time.sleep(0.01)
        [(name, secs)] = clk.items()
        assert name == "serialize" and secs >= 0.009

    def test_null_clock_is_falsy_and_inert(self):
        clk = phases.NULL_CLOCK
        assert not clk
        clk.record("fetch", 1.0)
        clk.move("fetch", "compile")
        assert clk.items() == () and clk.to_ms() == {}
        assert clk.total_s() == 0.0
        with clk.phase("fetch"):
            pass

    def test_activation_is_thread_local(self):
        clk = phases.PhaseClock()
        prev = phases.activate(clk)
        try:
            assert phases.current() is clk
            seen = []

            def other():
                seen.append(phases.current())

            t = threading.Thread(target=other)
            t.start()
            t.join()
            assert seen == [phases.NULL_CLOCK]
        finally:
            phases.restore(prev)
        assert phases.current() is phases.NULL_CLOCK

    def test_activate_nests(self):
        a, b = phases.PhaseClock(), phases.PhaseClock()
        p0 = phases.activate(a)
        p1 = phases.activate(b)
        assert phases.current() is b
        phases.restore(p1)
        assert phases.current() is a
        phases.restore(p0)

    def test_sixteen_thread_hammer_counts_exactly(self):
        # 16 threads hammer ONE clock: per-phase counts and sums must be
        # exact (the lock's whole job).
        clk = phases.PhaseClock()
        vocab = phases.PHASES
        # per is a multiple of the vocabulary size so every thread's
        # round-robin walk covers each phase exactly per/len(vocab)
        # times regardless of its starting offset.
        n_threads, per = 16, 70 * len(vocab)

        def worker(t):
            for i in range(per):
                clk.record(vocab[(t + i) % len(vocab)], 0.001)

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        counts = clk.counts()
        assert sum(counts.values()) == n_threads * per
        # Every thread walks the vocabulary round-robin from its own
        # offset, so each phase gets exactly (n_threads*per)/len(vocab).
        expected = n_threads * per // len(vocab)
        assert all(c == expected for c in counts.values()), counts
        assert clk.total_s() == pytest.approx(n_threads * per * 0.001)


class TestServerWiring:
    @pytest.fixture()
    def served(self, tmp_path):
        snap = kcc.synthetic_snapshot(48, seed=7)
        reg = MetricsRegistry()
        trace = tmp_path / "trace.jsonl"
        srv = CapacityServer(
            snap, port=0, registry=reg, trace_log=str(trace)
        )
        try:
            yield srv, reg, trace
        finally:
            srv.shutdown()

    def test_sweep_decomposes_into_phases(self, served):
        srv, reg, _ = served
        srv.dispatch(_sweep_msg())  # compile + staging land here
        srv.dispatch(_sweep_msg())
        rec = srv.flight_recorder.records()[-1]
        ph = rec.get("phases")
        assert ph, "flight record must carry the phase breakdown"
        # Steady state on a warm cache: the kernel phases must be
        # present; the cold-start-only phases must NOT be.
        assert {"device_exec", "fetch", "serialize"} <= set(ph)
        assert "compile" not in ph
        # Every emitted phase is in the vocabulary, and the sum of
        # phases never exceeds the end-to-end latency it decomposes.
        assert set(ph) <= set(phases.PHASES)
        assert sum(ph.values()) <= rec["latency_ms"] * 1.01 + 0.05

    def test_first_dispatch_attributes_compile(self, served):
        srv, _, _ = served
        srv.dispatch(_sweep_msg())
        rec = srv.flight_recorder.records()[-1]
        ph = rec.get("phases")
        # The xla_int64@n<bucket> label had never dispatched in this
        # registry... but compilewatch is process-global, so only assert
        # when this process really saw the first call.
        if "compile" in ph:
            assert ph["compile"] == max(ph.values())

    def test_phase_histogram_children_land_per_op_and_phase(self, served):
        srv, reg, _ = served
        srv.dispatch(_sweep_msg())
        fam = reg.snapshot()["kccap_phase_seconds"]
        assert fam["type"] == "histogram"
        labels = set(fam["values"])
        assert any('op="sweep"' in lb and 'phase="serialize"' in lb
                   for lb in labels)
        assert any('phase="queue_wait"' in lb for lb in labels)
        # Sub-ms resolution: the ladder must have boundaries below the
        # default's 0.5 ms floor, or phase p50s are unestimable.
        some = next(iter(fam["values"].values()))
        finite = [float(le) for le in some["buckets"] if le != "+Inf"]
        assert min(finite) < 0.0005

    def test_trace_log_carries_phase_child_spans(self, served):
        srv, _, trace = served
        srv.dispatch(_sweep_msg())
        lines = [
            json.loads(ln) for ln in trace.read_text().splitlines()
        ]
        parents = [ln for ln in lines if ln["op"] == "sweep"]
        children = [ln for ln in lines if ln["op"].startswith("phase:")]
        assert parents and children
        span_id = parents[-1]["span_id"]
        mine = [c for c in children if c["parent_span_id"] == span_id]
        assert mine, "phase spans must parent to the request span"
        for c in mine:
            assert c["phase"] in phases.PHASES
            assert c["op"] == f"phase:{c['phase']}"
            assert c["duration_ms"] >= 0

    def test_fit_records_serialize_phase(self, served):
        srv, _, _ = served
        srv.dispatch({"op": "fit", "cpuRequests": "100m",
                      "memRequests": "100mb", "replicas": "1"})
        rec = srv.flight_recorder.records()[-1]
        assert "serialize" in rec.get("phases", {})

    def test_dump_op_returns_phases(self, served):
        srv, _, _ = served
        srv.dispatch(_sweep_msg())
        dump = srv.dispatch({"op": "dump", "filter_op": "sweep"})
        assert dump["records"][-1].get("phases")


class TestReconciliation:
    """Sum-of-phases ≈ end-to-end, per request — with a deliberately
    injected slow phase so the tolerance is dominated by signal, not
    sub-millisecond jitter — and the slow phase is named as the top
    contributor."""

    @pytest.fixture()
    def slow_kernel(self, monkeypatch):
        from kubernetesclustercapacity_tpu.ops import fit as fit_mod

        real = fit_mod.sweep_grid

        def slowed(*a, **kw):
            time.sleep(0.06)
            return real(*a, **kw)

        monkeypatch.setattr(fit_mod, "sweep_grid", slowed)
        return 0.06

    def test_sum_of_phases_reconciles_and_names_the_culprit(
        self, slow_kernel
    ):
        snap = kcc.synthetic_snapshot(32, seed=9)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        try:
            srv.dispatch(_sweep_msg())  # compile with the sleep priced in
            for _ in range(3):
                srv.dispatch(_sweep_msg())
                rec = srv.flight_recorder.records()[-1]
                ph = rec["phases"]
                total = sum(ph.values())
                # The injected 60 ms dominates: sum-of-phases within 15%
                # of the end-to-end latency, per request.
                assert abs(total - rec["latency_ms"]) <= (
                    0.15 * rec["latency_ms"]
                ), (ph, rec["latency_ms"])
                top = max(ph, key=ph.get)
                assert top == "device_exec", ph
        finally:
            srv.shutdown()

    def test_slow_slot_wait_is_named_queue_wait(self):
        # A server with ONE compute slot and a long-running sweep on it:
        # the second request's decomposition must name queue_wait.
        from kubernetesclustercapacity_tpu.ops import fit as fit_mod

        snap = kcc.synthetic_snapshot(16, seed=10)
        srv = CapacityServer(
            snap, port=0, registry=MetricsRegistry(), max_inflight=1,
            batch_window_ms=0.0,
        )
        real = fit_mod.sweep_grid
        try:
            srv.dispatch(_sweep_msg())  # warm compile

            import unittest.mock as mock

            def slowed(*a, **kw):
                time.sleep(0.12)
                return real(*a, **kw)

            with mock.patch.object(fit_mod, "sweep_grid", slowed):
                t = threading.Thread(
                    target=srv.dispatch, args=(_sweep_msg(),)
                )
                t.start()
                time.sleep(0.03)  # let it take the slot
                srv.dispatch(_sweep_msg())
                t.join()
            rec = srv.flight_recorder.records()[-1]
            assert rec["phases"].get("queue_wait", 0) >= 50, rec
        finally:
            srv.shutdown()


class TestBatchWaitAttribution:
    def test_followers_record_batch_wait_leader_records_kernel(self):
        from kubernetesclustercapacity_tpu.service.batching import (
            MicroBatcher,
        )

        release = threading.Event()
        clocks: dict[int, phases.PhaseClock] = {}

        def dispatch(_key, items):
            release.wait(5)
            time.sleep(0.02)
            return [i for i in items]

        b = MicroBatcher(dispatch, window_s=0.3, max_batch=8)

        def worker(i):
            clk = phases.PhaseClock()
            clocks[i] = clk
            prev = phases.activate(clk)
            try:
                b.submit("k", i)
            finally:
                phases.restore(prev)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)
        release.set()
        for t in threads:
            t.join(10)
        waits = [c.to_ms().get("batch_wait", 0.0) for c in clocks.values()]
        # Every member (leader AND followers) recorded a batch_wait.
        assert all(w > 0 for w in waits), waits


class TestTelemetryOff:
    def test_new_clock_is_the_null_singleton(self, monkeypatch):
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        assert phases.new_clock() is phases.NULL_CLOCK

    def test_dispatch_allocates_no_clock_and_records_no_phases(
        self, monkeypatch
    ):
        # The strong pin: with telemetry off, a full server dispatch
        # must never CONSTRUCT a PhaseClock (zero allocations on the
        # dispatch path), and the flight record carries no phases.
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")

        def boom(cls):
            raise AssertionError(
                "PhaseClock allocated with KCCAP_TELEMETRY=0"
            )

        monkeypatch.setattr(
            phases.PhaseClock, "__new__", boom
        )
        snap = kcc.synthetic_snapshot(16, seed=11)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        try:
            r = srv.dispatch(_sweep_msg())
            assert r["scenarios"] == 4
            rec = srv.flight_recorder.records()[-1]
            assert "phases" not in rec
        finally:
            srv.shutdown()

    def test_phase_histogram_stays_childless_when_disabled(
        self, monkeypatch
    ):
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        snap = kcc.synthetic_snapshot(16, seed=12)
        reg = MetricsRegistry()
        srv = CapacityServer(snap, port=0, registry=reg)
        try:
            srv.dispatch(_sweep_msg())
            fam = reg.snapshot()["kccap_phase_seconds"]
            assert fam["values"] == {}  # family declared, zero observes
        finally:
            srv.shutdown()


class TestBenchBreakdown:
    @pytest.fixture()
    def bench_mod(self):
        sys.modules.pop("bench", None)
        sys.path.insert(0, _REPO_ROOT)
        try:
            import bench

            yield bench
        finally:
            sys.path.pop(0)
            sys.modules.pop("bench", None)

    def test_breakdown_reconciles_with_single_dispatch(
        self, bench_mod, monkeypatch
    ):
        """The acceptance shape: per-phase p50s sum to within 15% of an
        exact-single-dispatch-style p50 measured the same way bench.py
        measures it — with a deliberately slowed kernel so the check is
        signal-dominated — and the injected slow phase is named as the
        top contributor."""
        from kubernetesclustercapacity_tpu.ops import fit as fit_mod
        from kubernetesclustercapacity_tpu.ops.fit import (
            snapshot_device_arrays,
        )
        from kubernetesclustercapacity_tpu.utils.timing import (
            measure_latency,
        )

        snap = kcc.synthetic_snapshot(256, seed=21)
        grid = kcc.random_scenario_grid(16, seed=3)
        kcc.sweep_snapshot(snap, grid)  # pre-pay compile + staging

        real = fit_mod.sweep_grid

        def slowed(*a, **kw):
            time.sleep(0.05)
            return real(*a, **kw)

        monkeypatch.setattr(fit_mod, "sweep_grid", slowed)

        out = bench_mod._measure_dispatch_breakdown(snap, grid, reps=5)
        ph = out["phases_p50_ms"]
        assert set(ph) <= set(phases.PHASES)
        assert max(ph, key=ph.get) == "device_exec", ph

        # bench.py's exact_single_dispatch measurement shape, same
        # slowed kernel: device arrays staged once, p50 of 5 dispatches.
        arrays = snapshot_device_arrays(snap)
        cr = np.asarray(grid.cpu_request_milli)
        mr = np.asarray(grid.mem_request_bytes)
        rp = np.asarray(grid.replicas)
        single_p50 = measure_latency(
            lambda: np.asarray(
                slowed(*arrays, cr, mr, rp, mode="reference")[0]
            ),
            reps=5,
        ).p50
        assert abs(out["sum_of_phases_ms"] - single_p50) <= (
            0.15 * single_p50
        ), (out, single_p50)
        # And the breakdown's own e2e reconciles with its phases too.
        assert abs(out["sum_of_phases_ms"] - out["e2e_p50_ms"]) <= (
            0.15 * out["e2e_p50_ms"]
        ), out

    def test_breakdown_has_no_compile_after_warmup(self, bench_mod):
        snap = kcc.synthetic_snapshot(128, seed=22)
        grid = kcc.random_scenario_grid(8, seed=5)
        out = bench_mod._measure_dispatch_breakdown(snap, grid, reps=3)
        assert "compile" not in out["phases_p50_ms"], out
        assert out["sum_of_phases_ms"] <= out["e2e_p50_ms"] * 1.05 + 0.1


_KIND_FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "kind-3node.json"
)


class TestDumpCli:
    def test_kccap_dump_renders_phases(self, capsys):
        from kubernetesclustercapacity_tpu.cli import main as cli_main

        snap = kcc.synthetic_snapshot(16, seed=13)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        srv.start()
        try:
            host, port = srv.address
            srv.dispatch(_sweep_msg())
            rc = cli_main(["-dump", f"{host}:{port}"])
            out = capsys.readouterr().out
            assert rc == 0
            assert "flight recorder:" in out
            assert "phases:" in out
            assert "device_exec=" in out or "serialize=" in out
            rc = cli_main(
                ["-dump", f"{host}:{port}", "-output", "json",
                 "-dump-limit", "1"]
            )
            payload = json.loads(capsys.readouterr().out)
            assert rc == 0 and payload["count"] == 1
        finally:
            srv.shutdown()

    def test_bad_addr_errors(self, capsys):
        from kubernetesclustercapacity_tpu.cli import main as cli_main

        assert cli_main(["-dump", "nowhere"]) == 1
        assert "want HOST:PORT" in capsys.readouterr().err


class TestClientAttemptSpans:
    def test_retries_emit_one_child_span_per_attempt(self, tmp_path):
        from kubernetesclustercapacity_tpu.resilience import RetryPolicy
        from kubernetesclustercapacity_tpu.service.client import (
            CapacityClient,
        )
        from kubernetesclustercapacity_tpu.testing_faults import (
            FaultPlan,
            FaultProxy,
        )

        snap = kcc.synthetic_snapshot(8, seed=14)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        srv.start()
        proxy = FaultProxy(
            srv.address, FaultPlan(["drop_pre", "drop_pre"])
        )
        proxy.start()
        log = tmp_path / "client-trace.jsonl"
        try:
            with CapacityClient(
                *proxy.address,
                retry=RetryPolicy(
                    max_attempts=4, base_delay_s=0.01, max_delay_s=0.02
                ),
                trace=True,
                trace_log=str(log),
            ) as c:
                assert c.ping() == "pong"
            lines = [
                json.loads(ln) for ln in log.read_text().splitlines()
            ]
            calls = [ln for ln in lines if ln["op"] == "client:ping"]
            attempts = [ln for ln in lines if ln["op"] == "ping:attempt"]
            assert len(calls) == 1
            call = calls[0]
            assert call["status"] == "ok"
            # Two dropped attempts + the success = three attempt spans,
            # all parented to the one call span, indices 1..3.
            assert [a["attempt"] for a in attempts] == [1, 2, 3]
            assert all(
                a["parent_span_id"] == call["span_id"] for a in attempts
            )
            assert [a["status"] for a in attempts] == [
                "error", "error", "ok",
            ]
            # The backoff slept before each retry attempt is recorded.
            assert attempts[0]["backoff_ms"] == 0.0
            assert attempts[1]["backoff_ms"] > 0
            assert call["attempts"] == 3
            # The trace_id ties every span to the request's server span.
            assert all(
                a["trace_id"] == call["trace_id"] for a in attempts
            )
        finally:
            proxy.stop()
            srv.shutdown()

    def test_single_attempt_call_emits_call_and_attempt_span(
        self, tmp_path
    ):
        from kubernetesclustercapacity_tpu.service.client import (
            CapacityClient,
        )

        snap = kcc.synthetic_snapshot(8, seed=15)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        srv.start()
        log = tmp_path / "t.jsonl"
        try:
            with CapacityClient(
                *srv.address, trace_log=str(log)
            ) as c:
                c.ping()
            lines = [
                json.loads(ln) for ln in log.read_text().splitlines()
            ]
            assert [ln["op"] for ln in lines] == [
                "ping:attempt", "client:ping",
            ]
        finally:
            srv.shutdown()
