"""Device-memory ledger: identity bookkeeping, the budget signal, the
sustained-leak reconciler, the doctor line, and the
``KCCAP_MEMLEDGER=0`` zero-registry hatch.  (The 16-thread concurrency
hammer lives in ``analysis/hammer.py``; this file pins semantics.)"""

import pytest

from kubernetesclustercapacity_tpu.telemetry import memledger


class _Leaf:
    """Stands in for a device array: identity + ``nbytes`` is all the
    ledger reads (it takes no strong references)."""

    __slots__ = ("nbytes",)

    def __init__(self, nbytes: int) -> None:
        self.nbytes = nbytes


def _container(*sizes):
    return tuple(_Leaf(s) for s in sizes)


@pytest.fixture()
def ledger(monkeypatch):
    """A private book with the gauge side effects stubbed out — unit
    tests must not attach callbacks to the global registry (the enabled
    gauge path is exercised end-to-end via the server/devcache)."""
    led = memledger.DeviceLedger()
    monkeypatch.setattr(led, "_ensure_gauges", lambda form: None)
    return led


class TestBookkeeping:
    def test_register_books_leaf_bytes_by_form(self, ledger):
        c = _container(100, 28)
        assert ledger.register(c, "exact") == 128
        assert ledger.total_bytes() == 128
        assert ledger.form_bytes("exact") == 128
        assert ledger.peak_bytes() == 128

    def test_retire_releases_and_unknown_is_harmless(self, ledger):
        c = _container(64)
        ledger.register(c, "grouped")
        assert ledger.retire(c) == 64
        assert ledger.total_bytes() == 0
        # Retiring twice (or something never booked) returns 0 —
        # staying booked forever is the bug, not double-retiring.
        assert ledger.retire(c) == 0
        assert ledger.retire(object()) == 0

    def test_reregister_same_container_last_wins(self, ledger):
        c = _container(50)
        ledger.register(c, "exact")
        ledger.register(c, "grouped")  # devcache double-build race
        assert ledger.total_bytes() == 50
        assert ledger.form_bytes("exact") == 0
        assert ledger.form_bytes("grouped") == 50
        st = ledger.stats()
        assert st["entries"] == 1
        assert st["registered"] == 2 and st["retired"] == 1

    def test_peak_is_a_high_watermark(self, ledger):
        a, b = _container(100), _container(200)
        ledger.register(a, "exact")
        ledger.register(b, "exact")
        ledger.retire(a)
        ledger.retire(b)
        assert ledger.total_bytes() == 0
        assert ledger.peak_bytes() == 300

    def test_nested_containers_flatten_to_leaves(self, ledger):
        nested = (_Leaf(1), [_Leaf(2), (_Leaf(4), "not-a-leaf")], None)
        assert ledger.register(nested, "fold_fetch") == 7

    def test_dying_devcache_retires_its_booked_bytes(
        self, ledger, monkeypatch
    ):
        """A short-lived DeviceCache must un-book its entries when it is
        collected — otherwise the global book accrues stale leaves and
        the reconciler reports a false sustained leak (doctor FAILED
        after any tool that staged through an ephemeral cache)."""
        import gc

        from kubernetesclustercapacity_tpu import devcache

        monkeypatch.delenv("KCCAP_DEVCACHE", raising=False)
        monkeypatch.setattr(memledger, "LEDGER", ledger)

        class _Snap:
            pass

        cache = devcache.DeviceCache()
        cache.get(_Snap(), ("exact",), lambda: _container(4096))
        assert ledger.total_bytes() == 4096
        del cache
        gc.collect()
        assert ledger.total_bytes() == 0


class TestBudget:
    def test_budget_breach_is_a_signal_not_a_gate(self, ledger):
        ledger.set_budget(100)
        assert not ledger.budget_breached()
        c = _container(150)
        ledger.register(c, "exact")  # register still succeeds
        assert ledger.budget_breached()
        assert ledger.stats()["budget_breached"]
        ledger.retire(c)
        assert not ledger.budget_breached()
        ledger.set_budget(None)
        assert ledger.stats()["budget_bytes"] is None


class TestReconcile:
    def test_one_miss_is_a_suspect_two_is_a_leak(self, ledger):
        c = _container(10, 20)
        keep, lost = c
        ledger.register(c, "exact")
        # All leaves visible: clean.
        audit = ledger.reconcile(live_arrays=[keep, lost])
        assert audit["missing_bytes"] == 0 and not audit["leaking"]
        # First miss: suspect only — a concurrent eviction between our
        # snapshot and the backend's walk must not page anyone.
        audit = ledger.reconcile(live_arrays=[keep])
        assert audit["missing_bytes"] == 20
        assert audit["sustained_missing_bytes"] == 0
        assert not audit["leaking"] and not ledger.leaking()
        # Same leaf missing again: sustained — the alert trips.
        audit = ledger.reconcile(live_arrays=[keep])
        assert audit["sustained_missing_bytes"] == 20
        assert audit["leaking"] and ledger.leaking()
        assert ledger.stats()["leaked_bytes"] == 20
        # The leaf coming back clears suspect state and the alert.
        audit = ledger.reconcile(live_arrays=[keep, lost])
        assert audit["sustained_missing_bytes"] == 0
        assert not ledger.leaking()

    def test_reset_forgets_everything(self, ledger):
        c = _container(10)
        ledger.register(c, "exact")
        ledger.reconcile(live_arrays=[])
        ledger.reconcile(live_arrays=[])
        assert ledger.leaking()
        ledger.reset()
        assert ledger.total_bytes() == 0
        assert ledger.peak_bytes() == 0
        assert not ledger.leaking()
        assert ledger.stats()["reconciles"] == 0


class TestDoctorLine:
    def test_leak_line_is_failed(self, ledger, monkeypatch):
        monkeypatch.setattr(memledger, "LEDGER", ledger)
        c = _container(10)
        ledger.register(c, "exact")
        ledger.reconcile(live_arrays=[])
        ledger.reconcile(live_arrays=[])
        line = memledger.device_memory_status()
        assert line.startswith("FAILED: device-memory leak")

    def test_budget_line_is_failed(self, ledger, monkeypatch):
        monkeypatch.setattr(memledger, "LEDGER", ledger)
        ledger.set_budget(1)
        ledger.register(_container(100), "exact")
        line = memledger.device_memory_status()
        assert line.startswith("FAILED: device budget breached")

    def test_ok_line_carries_the_book(self, ledger, monkeypatch):
        monkeypatch.setattr(memledger, "LEDGER", ledger)
        ledger.register(_container(1 << 20), "exact")
        line = memledger.device_memory_status()
        assert line.startswith("ok:")
        assert "exact=1.0MiB" in line


class TestLedgerOff:
    def test_dedicated_hatch_disables(self, monkeypatch):
        monkeypatch.setenv("KCCAP_MEMLEDGER", "0")
        assert not memledger.enabled()

    def test_telemetry_off_disables_too(self, monkeypatch):
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        assert not memledger.enabled()

    def test_module_hooks_are_noops_when_off(self, monkeypatch):
        monkeypatch.setenv("KCCAP_MEMLEDGER", "0")
        led = memledger.DeviceLedger()
        monkeypatch.setattr(memledger, "LEDGER", led)
        memledger.register(_container(100), "exact")
        memledger.retire(_container(100))
        assert led.stats()["registered"] == 0

    def test_retire_still_unbooks_after_hatch_flip(
        self, ledger, monkeypatch
    ):
        """A buffer booked while armed must come off the book even if
        the hatch is thrown before its cache retires it — otherwise a
        telemetry-off window (hatch parity tests, an operator toggling
        the env) turns every retirement into a stale leaf and the
        reconciler reports a false sustained leak."""
        monkeypatch.setattr(memledger, "LEDGER", ledger)
        c = _container(512)
        memledger.register(c, "exact")
        assert ledger.total_bytes() == 512
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        memledger.retire(c)
        assert ledger.total_bytes() == 0

    def test_zero_registry_calls_when_off(self, monkeypatch):
        monkeypatch.setenv("KCCAP_MEMLEDGER", "0")
        from kubernetesclustercapacity_tpu.telemetry.metrics import (
            REGISTRY,
        )

        def boom(*a, **kw):
            raise AssertionError("registry touched with ledger off")

        monkeypatch.setattr(REGISTRY, "gauge", boom)
        # Even a DIRECT register books privately but must skip gauges.
        memledger.DeviceLedger().register(_container(8), "exact")

    def test_doctor_line_says_off(self, monkeypatch):
        monkeypatch.setenv("KCCAP_MEMLEDGER", "0")
        assert memledger.device_memory_status().startswith("off")
