"""Cross-spec request folding: the ISSUE-19 serving contract.

Concurrent requests whose pod specs DIFFER — across tenants, across
ops — fold into one padded scenario dispatch keyed only by
(generation, semantics, kernel family) and split per request on return.
The property under test is bit-exactness: every folded answer equals
the same request served solo, in both semantics modes and across the
KCCAP_GROUPING x KCCAP_DEVCACHE matrix; explain members of a mixed
batch (served by the fused sweep+explain super-kernel) match the
unbatched explain op field for field; and the evidence actually lands
(fold_rate, mean_folded_specs, the fetch_overlap phase on async folded
sweeps).
"""

import dataclasses
import threading

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.service import (
    CapacityClient,
    CapacityServer,
)
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot


def _random_specs(rng, k):
    """k sweep requests, every one a DIFFERENT spec (sizes 1-3)."""
    specs = []
    for _ in range(k):
        s = int(rng.integers(1, 4))
        specs.append(
            dict(
                cpu_request_milli=rng.integers(50, 2000, size=s).tolist(),
                mem_request_bytes=(
                    rng.integers(1, 2048, size=s) * (1 << 20)
                ).tolist(),
                replicas=rng.integers(1, 8, size=s).tolist(),
            )
        )
    return specs


def _snapshot(mode, grouping):
    # 2048 nodes / 23 distinct shapes clears the grouping node floor and
    # compression gate; 300 nodes stays safely under the floor so the
    # ungrouped dispatch is what actually runs.
    if grouping == "1":
        snap = synthetic_snapshot(2048, seed=5, shapes=23)
    else:
        snap = synthetic_snapshot(300, seed=5)
    if mode == "strict":
        healthy = snap.healthy.copy()
        healthy[::7] = False
        snap = dataclasses.replace(snap, semantics="strict", healthy=healthy)
    return snap


def _serve_folded(snap, specs, explains=(), window_ms=250.0):
    """One batched server; all requests released through a barrier so
    they land inside one fold window.  Returns (sweep results, explain
    results, batcher stats, flight records)."""
    srv = CapacityServer(
        snap, port=0, batch_window_ms=window_ms, batch_max=64
    )
    srv.start()
    try:
        results = [None] * len(specs)
        exp = [None] * len(explains)
        errors = []
        barrier = threading.Barrier(len(specs) + len(explains))

        def sweep(i):
            try:
                c = CapacityClient(*srv.address)
                barrier.wait()
                results[i] = c.sweep(**specs[i])
                c.close()
            except Exception as e:  # noqa: BLE001 - surfaced via errors
                errors.append(e)

        def explain(j):
            try:
                c = CapacityClient(*srv.address)
                barrier.wait()
                exp[j] = c.explain(**explains[j])
                c.close()
            except Exception as e:  # noqa: BLE001 - surfaced via errors
                errors.append(e)

        threads = [
            threading.Thread(target=sweep, args=(i,))
            for i in range(len(specs))
        ] + [
            threading.Thread(target=explain, args=(j,))
            for j in range(len(explains))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors
        return results, exp, dict(srv._batcher.stats), srv._flight.records()
    finally:
        srv.shutdown()


def _serve_solo(snap, specs, explains=()):
    srv = CapacityServer(snap, port=0, batch_window_ms=0.0)
    srv.start()
    try:
        c = CapacityClient(*srv.address)
        res = [c.sweep(**s) for s in specs]
        exp = [c.explain(**e) for e in explains]
        c.close()
        return res, exp
    finally:
        srv.shutdown()


class TestCrossSpecFoldParity:
    @pytest.mark.parametrize("mode", ("reference", "strict"))
    @pytest.mark.parametrize("grouping", ("0", "1"))
    @pytest.mark.parametrize("devc", ("0", "1"))
    def test_folded_bit_identical_to_solo(
        self, mode, grouping, devc, monkeypatch
    ):
        monkeypatch.setenv("KCCAP_GROUPING", grouping)
        monkeypatch.setenv("KCCAP_DEVCACHE", devc)
        snap = _snapshot(mode, grouping)
        rng = np.random.default_rng(1234 + (grouping == "1") * 2 + (devc == "1"))
        specs = _random_specs(rng, 6)
        folded, _, stats, _ = _serve_folded(snap, specs)
        solo, _ = _serve_solo(snap, specs)
        for i, (f, s) in enumerate(zip(folded, solo)):
            assert f["totals"] == s["totals"], i
            assert f["schedulable"] == s["schedulable"], i
            assert f["scenarios"] == s["scenarios"], i
        # The point of the exercise: DIFFERENT specs actually shared a
        # launch (the barrier puts all six well inside one window).
        assert stats["batched_requests"] >= 2
        assert stats["fold_rate"] > 0.0
        assert stats["mean_folded_specs"] > 1.0

    @pytest.mark.parametrize("mode", ("reference", "strict"))
    def test_mixed_sweep_explain_fold_matches_solo(self, mode):
        """Mixed batches ride the fused sweep+explain super-kernel:
        sweep members and explain members BOTH answer bit-identically
        to their unbatched twins."""
        snap = _snapshot(mode, "0")
        rng = np.random.default_rng(77)
        specs = _random_specs(rng, 4)
        explains = [
            dict(cpuRequests="150m", memRequests="3mb", replicas="5"),
            dict(cpuRequests="900m", memRequests="800mb", replicas="2"),
        ]
        folded, fexp, stats, _ = _serve_folded(snap, specs, explains)
        solo, sexp = _serve_solo(snap, specs, explains)
        for i, (f, s) in enumerate(zip(folded, solo)):
            assert f["totals"] == s["totals"], i
            assert f["schedulable"] == s["schedulable"], i
        for j, (f, s) in enumerate(zip(fexp, sexp)):
            assert f == s, j
        assert stats["batched_requests"] >= 2

    def test_cross_tenant_requests_fold(self):
        """Tenancy labels are pure attribution: requests from DIFFERENT
        tenants fold into one dispatch, answers split bit-exactly, and
        the FoldAccounting counters say whose work shared the launch
        (kccap_fold_cross_tenant_total > 0)."""
        from kubernetesclustercapacity_tpu.service.tenancy import (
            parse_tenants,
        )

        snap = _snapshot("reference", "0")
        specs = _random_specs(np.random.default_rng(9), 4)
        tm = parse_tenants(
            [
                {"name": "team-0", "rps": 1000},
                {"name": "team-1", "rps": 1000},
            ]
        )
        srv = CapacityServer(
            snap, port=0, batch_window_ms=250.0, batch_max=16, tenants=tm
        )
        srv.start()
        try:
            errors = []
            barrier = threading.Barrier(len(specs))
            results = [None] * len(specs)

            def issue(i):
                try:
                    c = CapacityClient(*srv.address)
                    barrier.wait()
                    results[i] = c.call(
                        "sweep", tenant=f"team-{i % 2}", **specs[i]
                    )
                    c.close()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=issue, args=(i,))
                for i in range(len(specs))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not errors, errors
            stats = srv._batcher.stats
            assert stats["batched_requests"] >= 2
            solo, _ = _serve_solo(snap, specs)
            for got, want in zip(results, solo):
                assert got["totals"] == want["totals"]
            metrics = srv.registry.snapshot()
            cross = metrics["kccap_fold_cross_tenant_total"]["values"]
            assert sum(cross.values()) >= 1
            folded = metrics["kccap_tenant_folded_requests_total"]["values"]
            assert sum(folded.values()) >= 4
            assert {"tenant=\"team-0\"", "tenant=\"team-1\""} <= set(
                folded
            ), folded
        finally:
            srv.shutdown()


class TestFoldEvidence:
    def test_folded_sweeps_record_fetch_overlap_phase(self, monkeypatch):
        """All-sweep folded batches dispatch async: every member's
        flight record shows a fetch_overlap phase (the deferred
        device->host materialization), and solo dispatches never do."""
        monkeypatch.setenv("KCCAP_TELEMETRY", "1")
        snap = _snapshot("reference", "0")
        specs = _random_specs(np.random.default_rng(3), 4)
        _folded, _, stats, records = _serve_folded(snap, specs)
        assert stats["batched_requests"] >= 2
        sweep_phases = [
            r["phases"] for r in records
            if r["op"] == "sweep" and r.get("phases")
        ]
        assert any(
            "fetch_overlap" in p for p in sweep_phases
        ), sweep_phases
        # And the solo twin never records one (batch of one is the
        # exact synchronous path).
        srv = CapacityServer(snap, port=0, batch_window_ms=0.0)
        srv.start()
        try:
            c = CapacityClient(*srv.address)
            c.sweep(**specs[0])
            c.close()
            solo_phases = [
                r["phases"] for r in srv._flight.records()
                if r["op"] == "sweep" and r.get("phases")
            ]
            assert solo_phases and all(
                "fetch_overlap" not in p for p in solo_phases
            )
        finally:
            srv.shutdown()

    def test_fold_stats_shape(self):
        """fold_rate / mean_folded_specs are well-defined before any
        traffic (0.0, not NaN/ZeroDivision)."""
        from kubernetesclustercapacity_tpu.service.batching import (
            MicroBatcher,
        )

        b = MicroBatcher(lambda k, items: list(items), window_s=0.01)
        st = b.stats
        assert st["fold_rate"] == 0.0
        assert st["mean_folded_specs"] == 0.0
