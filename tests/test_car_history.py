"""The audit-log empirical feed: per-pod usage extracted from recorded
generations, and its robustness contract — zero usage records or a
torn-tail-only log yields a typed InsufficientHistoryError (never an
empty-array crash, never a silent point fallback)."""

import dataclasses
import json
import os

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.audit import AuditError, AuditLog
from kubernetesclustercapacity_tpu.audit.log import AuditReader
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot
from kubernetesclustercapacity_tpu.stochastic import (
    InsufficientHistoryError,
    capacity_at_risk,
    extract_usage_history,
    parse_stochastic_spec,
)


def _record_generations(directory, snaps):
    with AuditLog(directory) as log:
        for gen, snap in enumerate(snaps, start=1):
            log.record_generation(snap, gen)


class TestExtraction:
    def test_observed_usage_becomes_an_empirical_distribution(self, tmp_path):
        snaps = [synthetic_snapshot(30, seed=s) for s in range(3)]
        d = str(tmp_path / "audit")
        _record_generations(d, snaps)
        history = extract_usage_history(d, "cpu")
        # Pod-weighted observations: every (node, generation) with pods
        # contributes pods_count observations of used // pods.
        want = {}
        total = 0
        for snap in snaps:
            used = np.asarray(snap.used_cpu_req_milli)
            pods = np.asarray(snap.pods_count)
            for u, p in zip(used, pods):
                if p > 0 and u > 0 and (u // p) >= 1:
                    want[int(u // p)] = want.get(int(u // p), 0) + int(p)
                    total += int(p)
        assert history.observations == total
        assert history.generations == 3
        got = dict(zip(history.values.tolist(), history.weights.tolist()))
        assert got == {k: float(v) for k, v in want.items()}
        # The distribution is consumable by the CaR engine end to end.
        emp = history.distribution()
        assert not emp.degenerate
        spec = parse_stochastic_spec({
            "usage": {"cpu": emp.to_wire(), "memory": "1gb"},
            "replicas": 10, "samples": 16,
        })
        r = capacity_at_risk(synthetic_snapshot(20, seed=9), spec)
        assert set(np.unique(r.samples_cpu)) <= set(
            history.values.tolist()
        )

    def test_memory_resource_and_reader_reuse(self, tmp_path):
        d = str(tmp_path / "audit")
        _record_generations(d, [synthetic_snapshot(20, seed=1)])
        reader = AuditReader.load(d)
        h = extract_usage_history(reader, "memory")
        assert h.resource == "memory" and h.observations > 0
        with pytest.raises(ValueError, match="resource"):
            extract_usage_history(reader, "gpu")

    def test_wrapped_and_zero_usage_rows_excluded(self, tmp_path):
        snap = synthetic_snapshot(10, seed=4)
        used = np.asarray(snap.used_cpu_req_milli).copy()
        pods = np.asarray(snap.pods_count).copy()
        used[0], pods[0] = np.int64(-5), 3  # wrapped carrier: excluded
        used[1], pods[1] = 0, 4  # zero usage: excluded
        used[2], pods[2] = 100, 0  # no pods: excluded
        snap = dataclasses.replace(
            snap, used_cpu_req_milli=used, pods_count=pods
        )
        d = str(tmp_path / "audit")
        _record_generations(d, [snap])
        h = extract_usage_history(d, "cpu", min_observations=1)
        assert (h.values >= 1).all()
        # None of the excluded rows' values leaked in.
        assert int(used[2]) // 1 not in (
            h.values.tolist() if pods[2] == 0 else []
        )


class TestInsufficientHistory:
    def test_missing_and_empty_directories_are_typed(self, tmp_path):
        with pytest.raises(InsufficientHistoryError):
            extract_usage_history(str(tmp_path / "nope"))
        empty = tmp_path / "empty"
        empty.mkdir()
        with pytest.raises(InsufficientHistoryError) as ei:
            extract_usage_history(str(empty))
        assert "no audit segments" in str(ei.value)

    def test_torn_tail_only_segment_is_typed(self, tmp_path):
        d = tmp_path / "audit"
        d.mkdir()
        # A segment holding ONLY an unterminated (torn) record: the
        # crash-tolerant loader recovers it to zero records, and the
        # extractor reports that as insufficient history, not a crash.
        (d / "audit-000001.jsonl").write_text(
            json.dumps({"kind": "checkpoint", "generation": 1})[:20]
        )
        with pytest.raises(InsufficientHistoryError) as ei:
            extract_usage_history(str(d))
        assert ei.value.generations == 0
        assert "torn tail" in str(ei.value) or "no generation" in str(
            ei.value
        )

    def test_zero_usage_generations_are_typed_with_counts(self, tmp_path):
        snap = synthetic_snapshot(6, seed=2)
        idle = dataclasses.replace(
            snap,
            used_cpu_req_milli=np.zeros(6, dtype=np.int64),
            pods_count=np.zeros(6, dtype=np.int64),
        )
        d = str(tmp_path / "audit")
        _record_generations(d, [idle, idle])
        with pytest.raises(InsufficientHistoryError) as ei:
            extract_usage_history(d, "cpu")
        assert ei.value.observations == 0 and ei.value.generations == 2
        assert "0 pod-usage observation" in str(ei.value)

    def test_min_observations_threshold(self, tmp_path):
        d = str(tmp_path / "audit")
        _record_generations(d, [synthetic_snapshot(4, seed=3)])
        h = extract_usage_history(d, "cpu", min_observations=1)
        with pytest.raises(InsufficientHistoryError):
            extract_usage_history(
                d, "cpu", min_observations=h.observations + 1
            )

    def test_mid_file_corruption_stays_a_hard_audit_error(self, tmp_path):
        d = str(tmp_path / "audit")
        _record_generations(
            d, [synthetic_snapshot(8, seed=s) for s in range(2)]
        )
        seg = os.path.join(d, sorted(os.listdir(d))[0])
        with open(seg, "r+", encoding="utf-8") as fh:
            fh.seek(10)
            fh.write("\x00\x00garbage")
        with pytest.raises(AuditError):
            extract_usage_history(d, "cpu")
