"""Deliberately-broken package for analyzer rule tests (never imported)."""
