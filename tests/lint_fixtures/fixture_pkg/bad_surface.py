"""Surface-conformance + hygiene fixtures."""

import json  # expect: hygiene-unused-import
import os

DOCUMENTED_METRIC = "kccap_fixture_documented_total"
ROGUE_METRIC = "kccap_fixture_rogue_total"  # expect: surface-metric
BAD_CASE_METRIC = "kccap_Fixture_BadCase_total"  # expect: surface-metric

DOCUMENTED_ENV = os.environ.get("KCCAP_FIXTURE_DOCUMENTED", "")
ROGUE_ENV = os.environ.get("KCCAP_FIXTURE_ROGUE", "")  # expect: surface-env


def build_parser(p):
    p.add_argument("-documented-flag", action="store_true")
    p.add_argument("-rogue-flag", action="store_true")  # expect: surface-flag
    return p
