"""Lock-discipline fixtures: one racy read, one racy write, one inline
suppression, one lock-held-by-convention helper."""

import threading


class Racy:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._errors = 0
        self._immutable = 42  # never written under lock -> unguarded OK

    def incr(self) -> None:
        with self._lock:
            self._count += 1
            self._errors += 1

    def racy_read(self) -> int:
        return self._count  # expect: lock-discipline

    def racy_write(self) -> None:
        self._count = 0  # expect: lock-discipline

    def config(self) -> int:
        return self._immutable  # init-only field: no finding

    def suppressed_read(self) -> int:
        return self._errors  # kccap: lint-ok[lock-discipline] fixture: deliberate racy display read

    def _total_locked(self) -> int:
        # *_locked convention: caller holds the lock; no finding.
        return self._count + self._errors
