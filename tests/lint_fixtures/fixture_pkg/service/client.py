"""Client fixture: reaches `ping`, never `mystery`."""


class FixtureClient:
    def call(self, op):
        return op

    def ping(self):
        return self.call("ping")
