"""Op-table fixture: `ping` is documented + client-reachable, `mystery`
is neither (two surface-op findings on the assignment line)."""


class FixtureServer:
    # expect: surface-op, surface-op
    _KNOWN_OPS = frozenset({"ping", "mystery"})
