"""Call-graph edge cases, pinned at exact lines:

* a jit root created by ``jax.jit(wrapper)`` where ``wrapper`` is a
  ``functools.wraps``-decorated closure (the decorator-factory idiom);
* a lambda passed to ``jax.jit`` whose body references a helper — the
  helper must become a root;
* threaded-class inference through inheritance: the lock is
  ctor-proven only in the base, under a name the heuristics would
  never accept (``_mu``).
"""

import functools
import threading
import time

import jax


def _decorate(f):
    @functools.wraps(f)
    def wrapper(x):
        t = time.time()  # expect: jit-purity
        return f(x) + t

    return jax.jit(wrapper)


@_decorate
def decorated_root(x):
    return x


def _lam_helper(x):
    return x * time.perf_counter()  # expect: jit-purity


jitted_lambda = jax.jit(lambda x: _lam_helper(x))


class _Base:
    def __init__(self) -> None:
        self._mu = threading.Lock()


class Derived(_Base):
    def __init__(self) -> None:
        super().__init__()
        self._hits = 0

    def incr(self) -> None:
        with self._mu:
            self._hits += 1

    def racy(self) -> int:
        return self._hits  # expect: lock-discipline
