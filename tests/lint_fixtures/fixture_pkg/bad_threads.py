"""Silent-thread-death fixtures: unprotected workers (module-level and
method targets) and a fully-protected control."""

import threading


def fragile_worker():
    open("/nonexistent-fixture-path")


def safe_worker():
    try:
        open("/nonexistent-fixture-path")
    except Exception:
        pass


def spawn():
    threading.Thread(target=fragile_worker, daemon=True).start()  # expect: hygiene-thread-death
    threading.Thread(target=safe_worker, daemon=True).start()


class Worker:
    def start(self) -> None:
        self._t = threading.Thread(target=self._run, daemon=True)  # expect: hygiene-thread-death
        self._t.start()

    def _run(self) -> None:
        while True:
            self._tick()

    def _tick(self) -> None:
        pass
