"""Stand-in registry module: calls into here from jitted code must be
flagged as host-subsystem escapes."""


def count() -> None:
    pass
