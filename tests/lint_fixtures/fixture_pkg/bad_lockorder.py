"""Lock-order fixtures: an interprocedural A→B edge against a lexical
B→A edge (the planted inversion), plus a consistently-ordered control
class that must produce nothing."""

import threading

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()


def take_a_then_b():
    with _LOCK_A:
        _grab_b()  # expect: lock-order


def _grab_b():
    with _LOCK_B:
        pass


def take_b_then_a():
    with _LOCK_B:
        with _LOCK_A:  # expect: lock-order
            pass


class Ordered:
    """Control: both methods take outer before inner — no cycle."""

    def __init__(self) -> None:
        self._lock_outer = threading.Lock()
        self._lock_inner = threading.Lock()

    def first(self) -> None:
        with self._lock_outer:
            with self._lock_inner:
                pass

    def second(self) -> None:
        with self._lock_outer:
            with self._lock_inner:
                pass
