"""Every jit-purity category, one per marked line.

The ``# expect:`` markers are parsed by ``tests/test_lint_rules.py``:
each names the rule(s) that must fire AT THAT LINE.  This module is
analyzed, never imported.
"""

import os
import random
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fixture_pkg.telemetry.metrics import count

_lock = threading.Lock()


@partial(jax.jit, static_argnames=("mode",))
def bad_kernel(x, mode="reference"):
    count()  # expect: jit-purity
    t = time.perf_counter()  # expect: jit-purity
    print("tracing", mode)  # expect: jit-purity
    r = random.random()  # expect: jit-purity
    flag = os.environ.get("FIXTURE_SWITCH", "0")  # expect: jit-purity
    with _lock:  # expect: jit-purity
        pass
    inner = threading.Lock()  # expect: jit-purity
    jax.debug.print("traced {x}", x=x)  # expect: jit-purity
    y = np.asarray(x)  # expect: jit-purity
    z = int(x)  # expect: jit-purity
    del inner, flag
    return jnp.sum(x) + z + y.sum() + t + r


def _helper(x):
    return x * time.time()  # expect: jit-purity


@jax.jit
def transitive_root(x):
    return _helper(x)
