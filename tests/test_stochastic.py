"""Stochastic capacity: distribution grammar, the deterministic
sampler, and the capacity-at-risk engine.

The two load-bearing properties, each pinned here:

* **seed-replay oracle parity** — 200+ randomized trials: the kernel
  path's quantiles/totals are bit-identical to an independent oracle
  that re-draws the same seeds and evaluates every sample through the
  sequential bug-compatible ``fit_arrays_python`` walk, reducing with
  its own implementation of the documented quantile rule — in BOTH
  semantics modes, with unhealthy nodes, node masks, and the Q1
  pod-cap overwrite in play;
* **deterministic dispatch** — the same seed yields bit-identical
  quantiles across grouped vs ungrouped (``KCCAP_GROUPING=0``) and
  bucketed vs unbucketed (``KCCAP_DEVCACHE=0``) dispatch, in both
  modes.
"""

import dataclasses
import json
import math

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.snapshot import (
    ClusterSnapshot,
    synthetic_snapshot,
)
from kubernetesclustercapacity_tpu.stochastic import (
    CaRResult,
    DistributionError,
    StochasticSpec,
    UsageDistribution,
    capacity_at_risk,
    car_oracle,
    default_samples,
    load_stochastic_spec,
    parse_distribution,
    parse_stochastic_spec,
    quantile_index,
    quantile_label,
    sample_key,
    sample_usage,
)
from kubernetesclustercapacity_tpu.stochastic.distributions import MAX_USAGE
from kubernetesclustercapacity_tpu.stochastic.car import fit_totals_numpy


class TestDistributionGrammar:
    def test_kinds_parse_and_quantity_codecs(self):
        d = parse_distribution("cpu", {"dist": "normal", "mean": "500m",
                                       "std": "150m"})
        assert (d.kind, d.mean, d.std) == ("normal", 500.0, 150.0)
        d = parse_distribution("memory", {"dist": "lognormal", "mean": "1gb",
                                          "sigma": 0.4})
        assert d.mean == float(1 << 30) and d.sigma == 0.4
        d = parse_distribution("cpu", {"dist": "point", "value": 250})
        assert d.value == 250 and d.degenerate
        d = parse_distribution(
            "cpu",
            {"dist": "empirical", "values": ["100m", 300], "weights": [3, 1]},
        )
        assert d.values == (100, 300) and d.weights == (3.0, 1.0)

    def test_bare_quantity_is_point_shorthand(self):
        assert parse_distribution("memory", "1gb").value == 1 << 30
        assert parse_distribution("cpu", 750).value == 750

    @pytest.mark.parametrize(
        "resource, data, fragment",
        [
            ("cpu", {"dist": "gauss"}, "dist must be one of"),
            ("cpu", {"dist": "normal"}, "needs 'mean'"),
            ("cpu", {"dist": "normal", "mean": "500m", "sigma": 1},
             "unknown field"),
            ("cpu", {"dist": "normal", "mean": "junk!", "std": 1},
             "bad cpu quantity"),
            ("memory", {"dist": "point", "value": "12wat"},
             "bad memory quantity"),
            ("cpu", {"dist": "point", "value": 0}, "[1, 2^62]"),
            ("cpu", {"dist": "point", "value": -5}, "[1, 2^62]"),
            ("cpu", {"dist": "normal", "mean": 100, "std": -1}, ">= 0"),
            ("cpu", {"dist": "lognormal", "mean": 100, "sigma": 9}, "<= 4"),
            ("cpu", {"dist": "empirical", "values": []}, "non-empty"),
            ("cpu", {"dist": "empirical", "values": [1, 2],
                     "weights": [1]}, "length"),
            ("cpu", {"dist": "empirical", "values": [1, 2],
                     "weights": [1, 0]}, "> 0"),
            ("cpu", 3.5, "mapping"),
            ("cpu", [1], "mapping"),
        ],
    )
    def test_malformed_rejected(self, resource, data, fragment):
        with pytest.raises(DistributionError) as ei:
            parse_distribution(resource, data)
        assert fragment in str(ei.value)

    def test_degenerate_detection(self):
        assert parse_distribution(
            "cpu", {"dist": "normal", "mean": 100, "std": 0}
        ).degenerate
        assert not parse_distribution(
            "cpu", {"dist": "normal", "mean": 100, "std": 1}
        ).degenerate
        assert parse_distribution(
            "cpu", {"dist": "empirical", "values": [5, 5]}
        ).degenerate
        assert parse_distribution(
            "cpu", {"dist": "lognormal", "mean": 100, "sigma": 0}
        ).degenerate

    def test_spec_parses_and_validates(self):
        spec = parse_stochastic_spec(
            {
                "usage": {"cpu": "500m", "memory": "1gb"},
                "replicas": "40",
                "samples": 16,
                "seed": 3,
                "confidence": 0.9,
            }
        )
        assert spec.replicas == 40 and spec.samples == 16
        assert spec.seed == 3 and spec.confidence == 0.9
        for doc, fragment in [
            ({}, "usage"),
            ({"usage": {"cpu": "1"}}, "both"),
            ({"usage": {"cpu": "1", "memory": "1gb", "gpu": 1}},
             "unknown resource"),
            ({"usage": {"cpu": "1", "memory": "1gb"}, "samples": 1},
             "samples"),
            ({"usage": {"cpu": "1", "memory": "1gb"}, "confidence": 1.0},
             "confidence"),
            ({"usage": {"cpu": "1", "memory": "1gb"}, "replicas": "x"},
             "replicas"),
            ({"usage": {"cpu": "1", "memory": "1gb"}, "extra": 1},
             "unknown field"),
        ]:
            with pytest.raises(DistributionError) as ei:
                parse_stochastic_spec(doc)
            assert fragment in str(ei.value)

    def test_spec_file_round_trip(self, tmp_path):
        p = tmp_path / "spec.json"
        p.write_text(json.dumps({
            "usage": {
                "cpu": {"dist": "normal", "mean": "500m", "std": "100m"},
                "memory": "1gb",
            },
            "replicas": 25,
            "seed": 9,
        }))
        spec = load_stochastic_spec(str(p))
        assert spec.cpu.kind == "normal" and spec.memory.value == 1 << 30
        assert spec.replicas == 25 and spec.seed == 9
        wire = spec.to_wire()
        # The wire echo re-parses to the same spec (round trip).
        again = parse_stochastic_spec(
            {k: v for k, v in wire.items() if k != "samples"}
        )
        assert again.cpu == spec.cpu and again.memory == spec.memory

    def test_default_samples_env(self, monkeypatch):
        monkeypatch.delenv("KCCAP_CAR_SAMPLES", raising=False)
        assert default_samples() == 64
        monkeypatch.setenv("KCCAP_CAR_SAMPLES", "128")
        assert default_samples() == 128
        monkeypatch.setenv("KCCAP_CAR_SAMPLES", "junk")
        assert default_samples() == 64
        monkeypatch.setenv("KCCAP_CAR_SAMPLES", "1")  # below the floor
        assert default_samples() == 64


class TestSampler:
    def test_same_seed_same_draws_different_streams_differ(self):
        d = UsageDistribution(kind="normal", mean=500.0, std=150.0)
        a = sample_usage(d, 64, sample_key(7, 0))
        b = sample_usage(d, 64, sample_key(7, 0))
        assert np.array_equal(a, b)
        assert not np.array_equal(a, sample_usage(d, 64, sample_key(8, 0)))
        assert not np.array_equal(a, sample_usage(d, 64, sample_key(7, 1)))

    def test_domain_clamped(self):
        # A distribution whose raw draws would go negative/huge clamps
        # into [1, 2^62] — every sample is a valid kernel divisor.
        d = UsageDistribution(kind="normal", mean=10.0, std=1e6)
        s = sample_usage(d, 256, sample_key(0, 0))
        assert s.min() >= 1 and s.max() <= MAX_USAGE
        d = UsageDistribution(kind="lognormal", mean=1e9, sigma=4.0)
        s = sample_usage(d, 256, sample_key(0, 1))
        assert s.min() >= 1 and s.max() <= MAX_USAGE

    def test_point_is_exact_and_empirical_stays_in_vocabulary(self):
        d = UsageDistribution(kind="point", value=123)
        assert np.array_equal(
            sample_usage(d, 5, sample_key(0, 0)), np.full(5, 123)
        )
        d = UsageDistribution(
            kind="empirical", values=(100, 200, 900), weights=(8.0, 1.0, 1.0)
        )
        s = sample_usage(d, 512, sample_key(3, 0))
        assert set(np.unique(s)) <= {100, 200, 900}
        # The 8x-weighted value dominates the draw.
        assert (s == 100).mean() > 0.5


def _random_snapshot(rng, n):
    """Adversarial little cluster: unhealthy rows, zero-allocatable
    rows, tight pod caps (Q1 overwrite territory), occasional huge
    usage (wrapped-headroom territory)."""
    alloc_cpu = rng.integers(0, 8000, size=n).astype(np.int64)
    alloc_mem = rng.integers(0, 1 << 34, size=n).astype(np.int64)
    used_cpu = rng.integers(0, 6000, size=n).astype(np.int64)
    used_mem = rng.integers(0, 1 << 33, size=n).astype(np.int64)
    if rng.random() < 0.3:  # overcommitted rows: used > alloc
        used_mem[rng.integers(0, n)] = np.int64(1 << 35)
    alloc_pods = rng.integers(0, 30, size=n).astype(np.int64)
    pods = rng.integers(0, 40, size=n).astype(np.int64)
    healthy = rng.random(n) > 0.2
    return ClusterSnapshot(
        names=[f"n{i}" for i in range(n)],
        alloc_cpu_milli=alloc_cpu,
        alloc_mem_bytes=alloc_mem,
        alloc_pods=alloc_pods,
        used_cpu_req_milli=used_cpu,
        used_cpu_lim_milli=used_cpu,
        used_mem_req_bytes=used_mem,
        used_mem_lim_bytes=used_mem,
        pods_count=pods,
        healthy=np.asarray(healthy, dtype=np.bool_),
        semantics="reference",
    )


def _random_spec(rng):
    kind = rng.choice(["normal", "lognormal", "empirical"])
    if kind == "normal":
        cpu = UsageDistribution(
            kind="normal",
            mean=float(rng.integers(50, 2000)),
            std=float(rng.integers(1, 800)),
        )
    elif kind == "lognormal":
        cpu = UsageDistribution(
            kind="lognormal",
            mean=float(rng.integers(50, 2000)),
            sigma=float(rng.uniform(0.05, 1.0)),
        )
    else:
        k = int(rng.integers(2, 6))
        cpu = UsageDistribution(
            kind="empirical",
            values=tuple(int(v) for v in rng.integers(1, 3000, size=k)),
            weights=tuple(float(w) for w in rng.uniform(0.5, 4.0, size=k)),
        )
    mem = UsageDistribution(
        kind="normal",
        mean=float(rng.integers(1 << 20, 1 << 30)),
        std=float(rng.integers(1, 1 << 28)),
    )
    return StochasticSpec(
        cpu=cpu,
        memory=mem,
        replicas=int(rng.integers(0, 200)),
        samples=int(rng.integers(2, 16)),
        seed=int(rng.integers(0, 1 << 16)),
    )


def _oracle_quantile_index(n, q):
    """The documented rule, implemented independently of car.py."""
    k = math.ceil(round(q * n, 9))
    return min(max(n - k, 0), n - 1)


def _sequential_oracle(snap, spec, mode, node_mask, quantiles):
    """Seed-replay + sequential bug-compatible walk + independent
    quantile selection: the strongest independent comparator."""
    n = spec.n_samples()
    cpu = sample_usage(spec.cpu, n, sample_key(spec.seed, 0))
    mem = sample_usage(spec.memory, n, sample_key(spec.seed, 1))
    totals = []
    for s in range(n):
        fits = fit_arrays_python(
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            snap.alloc_pods,
            snap.used_cpu_req_milli,
            snap.used_mem_req_bytes,
            snap.pods_count,
            int(cpu[s]),
            int(mem[s]),
            mode=mode,
            healthy=snap.healthy,
        )
        if node_mask is not None:
            # The kernel's node_mask zeroes after the mode epilogue —
            # same rule, applied to the scalar walk's output.
            fits = [
                f if node_mask[i] else 0 for i, f in enumerate(fits)
            ]
        totals.append(sum(int(f) for f in fits))
    totals = np.asarray(totals, dtype=np.int64)
    st = np.sort(totals, kind="stable")
    return totals, {
        q: int(st[_oracle_quantile_index(n, q)]) for q in quantiles
    }


class TestOracleParity:
    """Acceptance pin: 200+ randomized trials, both semantics modes."""

    @pytest.mark.parametrize("mode", ["reference", "strict"])
    def test_randomized_seed_replay_parity(self, mode):
        rng = np.random.default_rng(2026 if mode == "reference" else 2027)
        quantiles = (0.5, 0.9, 0.95, 0.99)
        for trial in range(110):
            n_nodes = int(rng.integers(1, 14))
            snap = _random_snapshot(rng, n_nodes)
            spec = _random_spec(rng)
            node_mask = None
            if rng.random() < 0.4:
                node_mask = rng.random(n_nodes) > 0.25
            got = capacity_at_risk(
                snap, spec, mode=mode, node_mask=node_mask,
                quantiles=quantiles, bindings=False,
            )
            want_totals, want_q = _sequential_oracle(
                snap, spec, mode, node_mask, quantiles
            )
            assert np.array_equal(got.totals, want_totals), (
                mode, trial, got.totals, want_totals,
            )
            assert got.quantiles == want_q, (mode, trial)
            # The numpy vectorized oracle (the 1M-scale comparator)
            # agrees with the sequential walk too.
            np_totals = fit_totals_numpy(
                snap.alloc_cpu_milli, snap.alloc_mem_bytes,
                snap.alloc_pods, snap.used_cpu_req_milli,
                snap.used_mem_req_bytes, snap.pods_count, snap.healthy,
                got.samples_cpu, got.samples_mem,
                mode=mode, node_mask=node_mask,
            )
            assert np.array_equal(np_totals, want_totals), (mode, trial)
            # Mean / prob-of-fit derive from the same totals.
            assert got.mean == float(
                want_totals.astype(np.float64).mean()
            )
            assert got.prob_fit == float(
                (want_totals >= spec.replicas).mean()
            )

    def test_car_oracle_helper_matches_engine(self):
        snap = synthetic_snapshot(40, seed=1)
        spec = parse_stochastic_spec({
            "usage": {
                "cpu": {"dist": "normal", "mean": "500m", "std": "200m"},
                "memory": {"dist": "lognormal", "mean": "1gb", "sigma": 0.5},
            },
            "replicas": 50, "samples": 64, "seed": 11,
        })
        for mode in ("reference", "strict"):
            got = capacity_at_risk(snap, spec, mode=mode, bindings=False)
            want = car_oracle(snap, spec, mode=mode)
            assert np.array_equal(got.totals, want.totals)
            assert got.quantiles == want.quantiles
            assert got.quantile_samples == want.quantile_samples
            assert got.mean == want.mean


class TestQuantileRule:
    def test_index_rule(self):
        assert quantile_index(64, 0.5) == 32
        assert quantile_index(64, 0.95) == 3
        assert quantile_index(64, 0.99) == 0
        assert quantile_index(10, 0.9) == 1  # float noise must not shift
        assert quantile_index(1, 0.99) == 0
        with pytest.raises(ValueError):
            quantile_index(10, 1.0)
        with pytest.raises(ValueError):
            quantile_index(10, 0.0)

    def test_confidence_semantics(self):
        # At least a q fraction of samples sit at/above the quantile.
        rng = np.random.default_rng(5)
        for _ in range(50):
            n = int(rng.integers(1, 200))
            q = float(rng.uniform(0.01, 0.99))
            totals = np.sort(rng.integers(0, 1000, size=n))
            i = quantile_index(n, q)
            assert (totals >= totals[i]).sum() / n >= q - 1e-12

    def test_labels(self):
        assert quantile_label(0.95) == "p95"
        assert quantile_label(0.5) == "p50"
        assert quantile_label(0.975) == "p97.5"


@pytest.fixture()
def degenerate_fleet():
    """1,280 nodes over 6 shapes: big enough for the grouped dispatch
    gate (node floor 1024), with unhealthy rows, a tight pod cap (Q1),
    for the cross-dispatch determinism pin."""
    snap = synthetic_snapshot(1280, seed=17, shapes=6)
    healthy = np.asarray(snap.healthy).copy()
    healthy[::7] = False
    pods = np.asarray(snap.alloc_pods).copy()
    pods[::5] = 3  # Q1 overwrite fires on these rows
    return dataclasses.replace(
        snap, healthy=healthy, alloc_pods=pods
    )


class TestDeterministicDispatch:
    """Satellite: same seed → bit-identical quantiles across every
    dispatch path (grouped/ungrouped × bucketed/unbucketed), both
    semantics modes, with unhealthy/masked rows and Q1 in play."""

    SPEC = StochasticSpec(
        cpu=UsageDistribution(kind="normal", mean=500.0, std=180.0),
        memory=UsageDistribution(kind="lognormal", mean=float(1 << 30),
                                 sigma=0.5),
        replicas=100,
        samples=24,
        seed=99,
    )

    @pytest.mark.parametrize("mode", ["reference", "strict"])
    @pytest.mark.parametrize("masked", [False, True])
    def test_paths_bit_identical(self, degenerate_fleet, monkeypatch,
                                 mode, masked):
        from kubernetesclustercapacity_tpu.snapshot import (
            grouped_for_dispatch,
        )

        snap = degenerate_fleet
        mask = None
        if masked:
            rng = np.random.default_rng(4)
            mask = rng.random(snap.n_nodes) > 0.3
        results = {}
        for grouping, devcache in (
            ("1", "1"), ("0", "1"), ("1", "0"), ("0", "0"),
        ):
            monkeypatch.setenv("KCCAP_GROUPING", grouping)
            monkeypatch.setenv("KCCAP_DEVCACHE", devcache)
            # A fresh equal snapshot per path: per-snapshot dispatch
            # memos must not let one path reuse another's decision.
            path_snap = dataclasses.replace(snap)
            if grouping == "1":
                assert grouped_for_dispatch(path_snap) is not None
            r = capacity_at_risk(
                path_snap, self.SPEC, mode=mode, node_mask=mask,
                bindings=False,
            )
            results[(grouping, devcache)] = r
        baseline = results[("1", "1")]
        for key, r in results.items():
            assert np.array_equal(r.totals, baseline.totals), key
            assert r.quantiles == baseline.quantiles, key
            assert r.mean == baseline.mean and r.prob_fit == baseline.prob_fit

    def test_wire_shape_and_schedulable(self, degenerate_fleet):
        r = capacity_at_risk(degenerate_fleet, self.SPEC, bindings=True)
        wire = r.to_wire()
        assert set(wire["quantiles"]) == {"p50", "p90", "p95", "p99"}
        assert set(wire["binding"]) == {"p50", "p90", "p95", "p99"}
        # Quantiles are monotone non-increasing in confidence.
        assert (
            wire["quantiles"]["p50"]
            >= wire["quantiles"]["p90"]
            >= wire["quantiles"]["p95"]
            >= wire["quantiles"]["p99"]
        )
        assert isinstance(r.schedulable, bool)
        # The quantile IS the fit of its realizing sample.
        for q, s_i in r.quantile_samples.items():
            assert r.quantiles[q] == int(r.totals[s_i])

    def test_result_repr_fields(self, degenerate_fleet):
        r = capacity_at_risk(
            degenerate_fleet, self.SPEC, quantiles=(0.5,), bindings=False
        )
        assert isinstance(r, CaRResult)
        assert r.n_samples == 24
        assert r.samples_cpu.shape == (24,) and r.samples_mem.shape == (24,)
