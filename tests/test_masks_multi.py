"""Constraint masks (config 5) and multi-resource fit (config 4) tests."""

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.fixtures import load_fixture, synthetic_fixture
from kubernetesclustercapacity_tpu.masks import (
    anti_affinity_existing_mask,
    combine_masks,
    node_affinity_mask,
    node_selector_mask,
    tolerations_mask,
)
from kubernetesclustercapacity_tpu.models import CapacityModel, PodSpec
from kubernetesclustercapacity_tpu.ops.fit import (
    fit_per_node_multi,
    sweep_grid_multi,
)
from kubernetesclustercapacity_tpu.snapshot import snapshot_from_fixture

MIB = 1024 * 1024
GIB = 1024 * MIB


@pytest.fixture(scope="module")
def kind_snap():
    fx = load_fixture("tests/fixtures/kind-3node.json")
    return snapshot_from_fixture(fx, semantics="strict")


class TestTolerations:
    def test_untolerated_control_plane_taint(self, kind_snap):
        mask = tolerations_mask(kind_snap, [])
        np.testing.assert_array_equal(mask, [False, True, True])

    def test_exists_toleration(self, kind_snap):
        tols = [{"key": "node-role.kubernetes.io/control-plane",
                 "operator": "Exists", "effect": "NoSchedule"}]
        assert tolerations_mask(kind_snap, tols).all()

    def test_equal_toleration_requires_value(self, kind_snap):
        tols = [{"key": "node-role.kubernetes.io/control-plane",
                 "operator": "Equal", "value": "wrong", "effect": "NoSchedule"}]
        np.testing.assert_array_equal(
            tolerations_mask(kind_snap, tols), [False, True, True]
        )
        tols[0]["value"] = ""  # taint value is ""
        assert tolerations_mask(kind_snap, tols).all()

    def test_tolerate_everything(self, kind_snap):
        assert tolerations_mask(kind_snap, [{"operator": "Exists"}]).all()

    def test_prefer_no_schedule_is_soft(self):
        fx = {"nodes": [{"name": "n", "allocatable": {"cpu": "4"},
                         "conditions": [{"type": "Ready", "status": "True"}],
                         "taints": [{"key": "k", "value": "v",
                                     "effect": "PreferNoSchedule"}]}],
              "pods": []}
        snap = snapshot_from_fixture(fx, semantics="strict")
        assert tolerations_mask(snap, []).all()


class TestSelectorsAffinity:
    def test_node_selector(self, kind_snap):
        mask = node_selector_mask(kind_snap, {"zone": "zone-0"})
        np.testing.assert_array_equal(mask, [False, True, False])
        assert node_selector_mask(kind_snap, None).all()

    def test_affinity_expressions(self, kind_snap):
        terms = [{"matchExpressions": [
            {"key": "zone", "operator": "In", "values": ["zone-0", "zone-1"]}]}]
        np.testing.assert_array_equal(
            node_affinity_mask(kind_snap, terms), [False, True, True]
        )
        terms = [{"matchExpressions": [
            {"key": "node-role.kubernetes.io/control-plane",
             "operator": "DoesNotExist"}]}]
        np.testing.assert_array_equal(
            node_affinity_mask(kind_snap, terms), [False, True, True]
        )

    def test_affinity_terms_are_ored(self, kind_snap):
        terms = [
            {"matchExpressions": [{"key": "zone", "operator": "In",
                                   "values": ["zone-0"]}]},
            {"matchExpressions": [{"key": "zone", "operator": "In",
                                   "values": ["zone-1"]}]},
        ]
        np.testing.assert_array_equal(
            node_affinity_mask(kind_snap, terms), [False, True, True]
        )

    def test_empty_term_matches_nothing(self, kind_snap):
        # kube-scheduler: a nil/empty nodeSelectorTerm selects NO nodes.
        assert not node_affinity_mask(kind_snap, [{}]).any()
        assert not node_affinity_mask(
            kind_snap, [{"matchExpressions": []}]
        ).any()

    def test_gt_lt(self):
        fx = {"nodes": [
            {"name": "a", "allocatable": {"cpu": "4"}, "labels": {"gen": "3"},
             "conditions": [{"type": "Ready", "status": "True"}]},
            {"name": "b", "allocatable": {"cpu": "4"}, "labels": {"gen": "7"},
             "conditions": [{"type": "Ready", "status": "True"}]}],
            "pods": []}
        snap = snapshot_from_fixture(fx, semantics="strict")
        terms = [{"matchExpressions": [
            {"key": "gen", "operator": "Gt", "values": ["5"]}]}]
        np.testing.assert_array_equal(
            node_affinity_mask(snap, terms), [False, True]
        )


class TestAntiAffinity:
    def test_existing_pods_repel(self, kind_snap):
        fx = load_fixture("tests/fixtures/kind-3node.json")
        fx["pods"][8]["labels"] = {"app": "web"}  # web pod on kind-worker
        mask = anti_affinity_existing_mask(kind_snap, fx, {"app": "web"})
        np.testing.assert_array_equal(mask, [True, False, True])

    def test_combine(self, kind_snap):
        a = np.array([True, True, False])
        b = np.array([True, False, True])
        np.testing.assert_array_equal(combine_masks(a, b), [True, False, False])
        np.testing.assert_array_equal(combine_masks(None, a, None), a)
        assert combine_masks(None, None) is None


class TestMultiResourceKernel:
    def _gpu_fixture(self):
        return {"nodes": [
            {"name": "gpu-a", "allocatable": {
                "cpu": "16", "memory": "64Gi", "pods": "110",
                "nvidia.com/gpu": "8", "ephemeral-storage": "200Gi"},
             "conditions": [{"type": "Ready", "status": "True"}]},
            {"name": "cpu-b", "allocatable": {
                "cpu": "64", "memory": "256Gi", "pods": "110",
                "ephemeral-storage": "500Gi"},
             "conditions": [{"type": "Ready", "status": "True"}]}],
            "pods": []}

    def test_gpu_binds(self):
        snap = snapshot_from_fixture(
            self._gpu_fixture(), semantics="strict",
            extended_resources=("ephemeral-storage", "nvidia.com/gpu"))
        alloc, used = snap.resource_matrix(
            ("cpu", "memory", "nvidia.com/gpu"))
        reqs = np.array([1000, GIB, 2], dtype=np.int64)
        fits = np.asarray(fit_per_node_multi(
            alloc, used, snap.alloc_pods, snap.pods_count, snap.healthy,
            reqs, mode="strict"))
        # gpu-a: min(16, 64, 4) = 4; cpu-b: no GPUs -> alloc 0 <= used 0 -> 0.
        np.testing.assert_array_equal(fits, [4, 0])

    def test_zero_request_excludes_resource(self):
        snap = snapshot_from_fixture(
            self._gpu_fixture(), semantics="strict",
            extended_resources=("nvidia.com/gpu",))
        alloc, used = snap.resource_matrix(("cpu", "memory", "nvidia.com/gpu"))
        reqs = np.array([1000, GIB, 0], dtype=np.int64)  # GPU-less pod
        fits = np.asarray(fit_per_node_multi(
            alloc, used, snap.alloc_pods, snap.pods_count, snap.healthy,
            reqs, mode="strict"))
        np.testing.assert_array_equal(fits, [16, 64])

    def test_multi_matches_two_resource_kernel(self):
        from kubernetesclustercapacity_tpu.ops.fit import fit_per_node
        fx = synthetic_fixture(50, seed=13)
        snap = snapshot_from_fixture(fx, semantics="strict")
        alloc, used = snap.resource_matrix(("cpu", "memory"))
        reqs = np.array([150, 200 * MIB], dtype=np.int64)
        multi = np.asarray(fit_per_node_multi(
            alloc, used, snap.alloc_pods, snap.pods_count, snap.healthy,
            reqs, mode="strict"))
        two = np.asarray(fit_per_node(
            snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
            snap.used_cpu_req_milli, snap.used_mem_req_bytes,
            snap.pods_count, snap.healthy, 150, 200 * MIB, mode="strict"))
        np.testing.assert_array_equal(multi, two)

    def test_sweep_with_per_scenario_masks(self):
        fx = synthetic_fixture(30, seed=14)
        snap = snapshot_from_fixture(fx, semantics="strict")
        alloc, used = snap.resource_matrix(("cpu", "memory"))
        reqs = np.tile(np.array([[100, MIB]], dtype=np.int64), (4, 1))
        masks = np.ones((4, 30), dtype=bool)
        masks[1, :] = False          # scenario 1: nothing feasible
        masks[2, ::2] = False        # scenario 2: half the nodes
        totals, sched = sweep_grid_multi(
            alloc, used, snap.alloc_pods, snap.pods_count, snap.healthy,
            reqs, np.ones(4, dtype=np.int64), mode="strict",
            node_masks=masks)
        totals = np.asarray(totals)
        assert totals[1] == 0
        assert totals[0] == totals[3]
        assert totals[2] < totals[0]
        assert not np.asarray(sched)[1]


class TestCapacityModel:
    def test_spread_one_per_node(self, kind_snap):
        model = CapacityModel(kind_snap, mode="strict")
        spec = PodSpec(cpu_request_milli=100, mem_request_bytes=MIB,
                       replicas=2, spread=1)
        r = model.evaluate(spec)
        # Control-plane taint is untolerated (the mask applies whenever the
        # snapshot has taints), workers clamp to 1 replica each.
        np.testing.assert_array_equal(r.fits, [0, 1, 1])
        assert r.schedulable

    def test_spread_zero_rejected(self):
        with pytest.raises(ValueError, match="spread"):
            PodSpec(cpu_request_milli=100, mem_request_bytes=MIB, spread=0)

    def test_spread_with_toleration_covers_all_nodes(self, kind_snap):
        model = CapacityModel(kind_snap, mode="strict")
        spec = PodSpec(cpu_request_milli=100, mem_request_bytes=MIB,
                       replicas=3, spread=1,
                       tolerations=({"operator": "Exists"},))
        r = model.evaluate(spec)
        np.testing.assert_array_equal(r.fits, [1, 1, 1])
        assert r.schedulable

    def test_constraints_compose(self, kind_snap):
        fx = load_fixture("tests/fixtures/kind-3node.json")
        fx["pods"][8]["labels"] = {"app": "web"}
        model = CapacityModel(kind_snap, mode="strict", fixture=fx)
        spec = PodSpec(
            cpu_request_milli=100, mem_request_bytes=MIB, replicas=2,
            anti_affinity_labels={"app": "web"},  # excludes kind-worker
        )
        r = model.evaluate(spec)
        assert r.fits[0] == 0  # control-plane taint untolerated
        assert r.fits[1] == 0  # anti-affinity
        assert r.fits[2] > 0

    def test_gpu_spec(self):
        fx = {"nodes": [
            {"name": "g", "allocatable": {
                "cpu": "16", "memory": "64Gi", "pods": "110",
                "nvidia.com/gpu": "8"},
             "conditions": [{"type": "Ready", "status": "True"}]}],
            "pods": []}
        snap = snapshot_from_fixture(fx, semantics="strict",
                                     extended_resources=("nvidia.com/gpu",))
        model = CapacityModel(snap, mode="strict")
        r = model.evaluate(PodSpec(
            cpu_request_milli=1000, mem_request_bytes=GIB, replicas=4,
            extended_requests={"nvidia.com/gpu": 2}))
        assert r.total == 4
        assert r.schedulable

    def test_reference_mode_unconstrained_stays_bit_exact(self):
        """reference-mode model paths must agree with the uint64 oracle even
        on wrapped CPU bit patterns (the multi kernel would diverge)."""
        from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
        from kubernetesclustercapacity_tpu.snapshot import ClusterSnapshot
        from kubernetesclustercapacity_tpu.scenario import ScenarioGrid

        n = 4
        snap = ClusterSnapshot(
            names=[f"n{i}" for i in range(n)],
            alloc_cpu_milli=np.array([5000, 8000, 100, 700]),
            alloc_mem_bytes=np.full(n, 64 * GIB),
            alloc_pods=np.full(n, 110),
            used_cpu_req_milli=np.array([-1, 650, 0, 0]),  # -1 = uint64 max
            used_cpu_lim_milli=np.zeros(n),
            used_mem_req_bytes=np.zeros(n),
            used_mem_lim_bytes=np.zeros(n),
            pods_count=np.zeros(n),
            healthy=np.ones(n, dtype=bool),
        )
        model = CapacityModel(snap, mode="reference")
        spec = PodSpec(cpu_request_milli=100, mem_request_bytes=MIB, replicas=1)
        expected = fit_arrays_python(
            snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
            snap.used_cpu_req_milli, snap.used_mem_req_bytes,
            snap.pods_count, 100, MIB)
        np.testing.assert_array_equal(model.evaluate(spec).fits, expected)
        # node 0: alloc 5000 <= used (uint64 max) -> 0, NOT a huge int64 fit.
        assert model.evaluate(spec).fits[0] == 0
        grid = ScenarioGrid(np.array([100]), np.array([MIB]), np.array([1]))
        totals, _ = model.sweep(grid)
        assert totals[0] == sum(expected)

    def test_reference_mode_constraints_need_allow_extensions(self, kind_snap):
        model = CapacityModel(kind_snap, mode="reference",
                              allow_extensions=False)
        spec = PodSpec(cpu_request_milli=100, mem_request_bytes=MIB,
                       node_selector={"zone": "zone-0"})
        with pytest.raises(ValueError, match="extensions"):
            model.evaluate(spec)
        from kubernetesclustercapacity_tpu.scenario import ScenarioGrid
        grid = ScenarioGrid(np.array([100]), np.array([MIB]), np.array([1]))
        with pytest.raises(ValueError, match="extensions"):
            model.sweep(grid, node_selector={"zone": "zone-0"})
        # Unconstrained reference sweep does NOT mask tainted nodes.
        totals, _ = model.sweep(grid)
        assert totals[0] > 0

    def test_cpu_strict_backend_matches_kernel(self):
        from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
        from kubernetesclustercapacity_tpu.ops.fit import fit_per_node

        fx = synthetic_fixture(40, seed=17, unhealthy_frac=0.3)
        snap = snapshot_from_fixture(fx, semantics="strict")
        py = fit_arrays_python(
            snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
            snap.used_cpu_req_milli, snap.used_mem_req_bytes,
            snap.pods_count, 150, MIB, mode="strict", healthy=snap.healthy)
        jx = np.asarray(fit_per_node(
            snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
            snap.used_cpu_req_milli, snap.used_mem_req_bytes,
            snap.pods_count, snap.healthy, 150, MIB, mode="strict"))
        np.testing.assert_array_equal(py, jx)

    def test_model_sweep_with_tolerations(self, kind_snap):
        from kubernetesclustercapacity_tpu.scenario import ScenarioGrid, Scenario
        model = CapacityModel(kind_snap, mode="strict")
        grid = ScenarioGrid.from_scenarios(
            [Scenario(100, MIB, 1), Scenario(200, 2 * MIB, 1)])
        untol, _ = model.sweep(grid)
        tol, _ = model.sweep(grid, tolerations=({"operator": "Exists"},))
        assert (tol > untol).all()  # control-plane becomes available


class TestSweepMulti:
    """CapacityModel.sweep_multi: the R-resource production sweep surface
    (config 4) over MultiResourceGrid, auto-dispatching the fused kernel."""

    def _snap(self, n=600, seed=41):
        fx = synthetic_fixture(n, seed=seed)
        rng = np.random.default_rng(seed)
        for node in fx["nodes"]:
            node["allocatable"]["nvidia.com/gpu"] = str(
                int(rng.integers(0, 9))
            )
        return snapshot_from_fixture(
            fx, semantics="strict", extended_resources=("nvidia.com/gpu",)
        )

    def _grid(self, s=24, seed=42):
        from kubernetesclustercapacity_tpu.scenario import (
            MultiResourceGrid,
            random_scenario_grid,
        )

        rng = np.random.default_rng(seed)
        base = random_scenario_grid(s, seed=seed)
        return MultiResourceGrid.from_grid(
            base, {"nvidia.com/gpu": rng.integers(0, 3, s)}
        )

    def test_matches_exact_kernel(self):
        snap = self._snap()
        grid = self._grid()
        model = CapacityModel(snap, mode="strict")
        totals, sched = model.sweep_multi(grid)
        alloc_rn, used_rn = snap.resource_matrix(grid.resources)
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, grid.requests, grid.replicas, mode="strict",
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))
        np.testing.assert_array_equal(sched, np.asarray(exact[1]))

    def test_constraints_and_spread_compose(self, kind_snap):
        from kubernetesclustercapacity_tpu.scenario import (
            MultiResourceGrid,
        )

        grid = MultiResourceGrid(
            resources=("cpu", "memory"),
            requests=np.array([[100, 64 * MIB]], dtype=np.int64),
            replicas=np.array([1], dtype=np.int64),
        )
        model = CapacityModel(kind_snap, mode="strict")
        unconstrained, _ = model.sweep_multi(grid)
        selected, _ = model.sweep_multi(
            grid, node_selector={"kubernetes.io/hostname": "kind-worker"}
        )
        assert selected[0] < unconstrained[0]
        spread1, _ = model.sweep_multi(grid, spread=1)
        # kind has 3 nodes; control-plane is hard-tainted in strict mode.
        assert spread1[0] == 2

    def test_grid_validation(self):
        from kubernetesclustercapacity_tpu.scenario import (
            MultiResourceGrid,
            ScenarioError,
        )

        with pytest.raises(ScenarioError, match="cpu"):
            MultiResourceGrid(
                resources=("cpu", "memory"),
                requests=np.array([[0, MIB]]),
                replicas=np.array([1]),
            ).validate()
        with pytest.raises(ScenarioError, match="requests"):
            MultiResourceGrid(
                resources=("cpu", "memory"),
                requests=np.array([[1, 2, 3]]),
                replicas=np.array([1]),
            )


class TestSchedulerFidelity:
    """Round-4 review items: matchFields, anti-affinity namespace scoping,
    and core-resource aliasing in extended_requests."""

    def _snap(self):
        from kubernetesclustercapacity_tpu.snapshot import snapshot_from_fixture

        fx = {
            "nodes": [
                {"name": f"n{i}",
                 "allocatable": {"cpu": "4", "memory": "8388608Ki",
                                 "pods": "110"},
                 "conditions": [{"type": "c", "status": "False"}] * 4,
                 "labels": {"zone": f"z{i % 2}"}}
                for i in range(3)
            ],
            "pods": [
                {"name": "db-web", "namespace": "web", "nodeName": "n0",
                 "phase": "Running", "labels": {"app": "db"},
                 "containers": []},
                {"name": "db-staging", "namespace": "staging",
                 "nodeName": "n1", "phase": "Running",
                 "labels": {"app": "db"}, "containers": []},
            ],
        }
        return fx, snapshot_from_fixture(fx, semantics="strict")

    def test_match_fields_metadata_name(self):
        from kubernetesclustercapacity_tpu.masks import node_affinity_mask

        _, snap = self._snap()
        # The DaemonSet-controller pattern: pin to one node by name.
        mask = node_affinity_mask(
            snap,
            [{"matchFields": [{"key": "metadata.name", "operator": "In",
                               "values": ["n1"]}]}],
        )
        assert mask.tolist() == [False, True, False]
        # Expressions AND fields within one term.
        mask = node_affinity_mask(
            snap,
            [{"matchExpressions": [{"key": "zone", "operator": "In",
                                    "values": ["z0"]}],
              "matchFields": [{"key": "metadata.name", "operator": "NotIn",
                               "values": ["n0"]}]}],
        )
        assert mask.tolist() == [False, False, True]  # z0 minus n0 = n2

    def test_anti_affinity_namespace_scoping(self):
        from kubernetesclustercapacity_tpu.masks import (
            anti_affinity_existing_mask,
        )

        fx, snap = self._snap()
        # Cluster-wide (no namespace): both db pods repel.
        mask = anti_affinity_existing_mask(snap, fx, {"app": "db"})
        assert mask.tolist() == [False, False, True]
        # Scoped to 'web' (real PodAffinityTerm default): only n0 repels.
        mask = anti_affinity_existing_mask(
            snap, fx, {"app": "db"}, namespace="web"
        )
        assert mask.tolist() == [False, True, True]

    def test_extended_request_core_alias_rejected(self):
        import pytest as _pytest

        from kubernetesclustercapacity_tpu.models import PodSpec

        with _pytest.raises(ValueError, match="aliases a core resource"):
            PodSpec(cpu_request_milli=500, mem_request_bytes=1 << 30,
                    extended_requests={"cpu": 2})
