"""Federated multi-cluster capacity: the degradation-contract chaos suite.

The acceptance bar (ISSUE 12): under a seeded partition of 1-of-3
clusters, every ``fed_sweep`` reply is bit-identical to the per-cluster
sequential oracle at each cluster's STAMPED generation for fresh
clusters, the partitioned cluster is explicitly marked ``stale`` with a
bounded age (injectable clock), flips to ``lost`` past the eviction
horizon (excluded from totals AND named), and recovers to ``fresh``
after heal — with per-cluster watermarks monotone throughout and zero
silently-wrong totals, in both semantics modes.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.federation import (
    ClusterFeed,
    FederationError,
    FederationServer,
)
from kubernetesclustercapacity_tpu.federation.server import concat_snapshots
from kubernetesclustercapacity_tpu.fixtures import load_fixture
from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.resilience import ClusterLostError
from kubernetesclustercapacity_tpu.service.client import CapacityClient
from kubernetesclustercapacity_tpu.service.plane import (
    PlanePublisher,
    PlaneSubscriber,
)
from kubernetesclustercapacity_tpu.service.replicaset import ReplicaSet
from kubernetesclustercapacity_tpu.service.server import CapacityServer
from kubernetesclustercapacity_tpu.snapshot import (
    snapshot_from_fixture,
    synthetic_snapshot,
)
from kubernetesclustercapacity_tpu.telemetry.metrics import MetricsRegistry
from kubernetesclustercapacity_tpu.testing_faults import FaultPlan, FaultProxy

KIND = "tests/fixtures/kind-3node.json"

CPU = [100, 500, 900]
MEM = [10 ** 8, 5 * 10 ** 8, 10 ** 9]
REPS = [1, 8, 64]
GRID = {
    "cpu_request_milli": CPU,
    "mem_request_bytes": MEM,
    "replicas": REPS,
}


def _wait_for(predicate, timeout_s=10.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def _mutate(snap, seed):
    """A derived generation: deterministic usage churn (same shape/
    names, different fit answers)."""
    rng = np.random.default_rng(seed)
    used = snap.used_cpu_req_milli + rng.integers(
        0, 200, size=snap.n_nodes, dtype=np.int64
    )
    return dataclasses.replace(snap, used_cpu_req_milli=used)


def _oracle_totals(snap, cpu=CPU, mem=MEM):
    """Per-cluster sequential oracle: [S] totals for one snapshot, with
    the same implicit strict-taint mask every serving surface applies."""
    mask = implicit_taint_mask(snap)
    healthy = snap.healthy if mask is None else snap.healthy & mask
    return [
        sum(
            fit_arrays_python(
                snap.alloc_cpu_milli, snap.alloc_mem_bytes, snap.alloc_pods,
                snap.used_cpu_req_milli, snap.used_mem_req_bytes,
                snap.pods_count, int(c), int(m), mode=snap.semantics,
                healthy=healthy,
            )
        )
        for c, m in zip(cpu, mem)
    ]


def _cluster_snaps(semantics, n=48):
    """Three deterministic, distinct cluster snapshots; strict mode gets
    unhealthy rows and a taint so the mask path is non-vacuous."""
    snaps = {}
    for i, name in enumerate(("east", "west", "north")):
        snap = synthetic_snapshot(n + 8 * i, seed=30 + i)
        if semantics == "strict":
            healthy = snap.healthy.copy()
            healthy[i] = False
            taints = [[] for _ in range(snap.n_nodes)]
            taints[2 * i + 1] = [
                {"key": "dedicated", "value": "x", "effect": "NoSchedule"}
            ]
            snap = dataclasses.replace(
                snap, semantics="strict", healthy=healthy, taints=taints
            )
        snaps[name] = snap
    return snaps


# ---------------------------------------------------------------------------
# ClusterFeed + the state machine (offline, injectable clock)
# ---------------------------------------------------------------------------
class TestClusterFeed:
    def test_generation_watermark_monotone(self):
        feed = ClusterFeed("c", clock=lambda: 0.0)
        snap = synthetic_snapshot(8, seed=1)
        feed.replace_snapshot(snap, generation=5)
        assert feed.view() == (snap, 5)
        with pytest.raises(ValueError, match="must not regress"):
            feed.replace_snapshot(snap, generation=3)
        # Equal re-stage is idempotent redelivery (the subscriber's
        # digest-checked path), never a regression.
        feed.replace_snapshot(snap, generation=5)
        # Un-numbered stages increment locally.
        feed.replace_snapshot(snap)
        assert feed.view()[1] == 6

    def test_verified_age_tracks_injected_clock(self):
        now = [100.0]
        feed = ClusterFeed("c", clock=lambda: now[0])
        assert feed.last_verified_age_s() is None
        feed.replace_snapshot(synthetic_snapshot(4, seed=2))
        now[0] = 107.5
        assert feed.last_verified_age_s() == pytest.approx(7.5)


class TestDegradationStates:
    def _fed(self, **kw):
        kw.setdefault("stale_after_s", 5.0)
        kw.setdefault("evict_after_s", 20.0)
        return FederationServer(**kw)

    def test_never_synced_is_lost(self):
        now = [0.0]
        with self._fed(clock=lambda: now[0]) as fed:
            fed.attach("ghost", ("127.0.0.1", 1))  # nothing listens there
            status = fed.status()
            assert status["clusters"]["ghost"]["state"] == "lost"
            assert status["excluded"] == ["ghost"]
            assert not fed.healthy()

    def test_fresh_stale_lost_transitions_at_exact_bounds(self):
        now = [0.0]
        with self._fed(clock=lambda: now[0]) as fed:
            fed.inject("c", synthetic_snapshot(8, seed=3))

            def state():
                return fed.status()["clusters"]["c"]["state"]

            assert state() == "fresh"
            now[0] = 5.0  # == stale_after_s: inclusive fresh
            assert state() == "fresh"
            now[0] = 5.001
            assert state() == "stale"
            now[0] = 20.0  # == evict_after_s: inclusive stale
            assert state() == "stale"
            assert fed.healthy()
            now[0] = 20.001
            assert state() == "lost"
            assert not fed.healthy()
            # Heal: a new verified stage flips straight back to fresh.
            fed.inject("c", synthetic_snapshot(8, seed=3))
            assert state() == "fresh" and fed.healthy()

    def test_horizon_validation(self):
        with pytest.raises(ValueError, match="must exceed"):
            FederationServer(stale_after_s=10.0, evict_after_s=10.0)
        with pytest.raises(ValueError, match="stale_after_s"):
            FederationServer(stale_after_s=0.0, evict_after_s=1.0)

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("KCCAP_FED_STALE_AFTER_S", "3.5")
        monkeypatch.setenv("KCCAP_FED_EVICT_AFTER_S", "7.25")
        with FederationServer() as fed:
            assert fed.stale_after_s == 3.5
            assert fed.evict_after_s == 7.25

    def test_duplicate_cluster_refused(self):
        with self._fed() as fed:
            fed.inject("c", synthetic_snapshot(4, seed=4))
            with pytest.raises(FederationError, match="duplicate"):
                fed._register("c", ClusterFeed("c"), None)


# ---------------------------------------------------------------------------
# Query semantics vs the sequential oracle (offline)
# ---------------------------------------------------------------------------
class TestFedQueriesOracle:
    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    def test_fed_sweep_per_cluster_bit_exact(self, semantics):
        now = [0.0]
        with FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, clock=lambda: now[0]
        ) as fed:
            snaps = _cluster_snaps(semantics)
            for i, (name, snap) in enumerate(snaps.items()):
                fed.inject(name, snap, generation=i + 1)
            r = fed.dispatch({"op": "fed_sweep", **GRID})
            grand = [0] * len(CPU)
            for name, snap in snaps.items():
                want = _oracle_totals(snap)
                assert r["per_cluster"][name] == want, name
                grand = [g + w for g, w in zip(grand, want)]
                assert r["clusters"][name]["state"] == "fresh"
            assert r["totals"] == grand
            assert r["schedulable"] == [t >= k for t, k in zip(grand, REPS)]
            assert r["excluded"] == [] and not r["degraded"]

    def test_mixed_semantics_groups_stay_exact(self):
        """A reference cluster and a strict cluster federate: one
        dispatch per semantics group, both bit-exact."""
        with FederationServer(stale_after_s=5.0, evict_after_s=20.0) as fed:
            ref = synthetic_snapshot(40, seed=50)
            strict = dataclasses.replace(
                synthetic_snapshot(52, seed=51), semantics="strict"
            )
            fed.inject("ref", ref)
            fed.inject("strict", strict)
            r = fed.dispatch({"op": "fed_sweep", **GRID})
            assert r["per_cluster"]["ref"] == _oracle_totals(ref)
            assert r["per_cluster"]["strict"] == _oracle_totals(strict)

    def test_stale_cluster_counted_but_annotated(self):
        now = [0.0]
        with FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, clock=lambda: now[0]
        ) as fed:
            snaps = _cluster_snaps("reference")
            for name, snap in snaps.items():
                fed.inject(name, snap)
            now[0] = 8.0
            for name, snap in snaps.items():
                if name != "east":
                    fed.inject(name, snap)  # the survivors re-verify
            r = fed.dispatch({"op": "fed_sweep", **GRID})
            assert r["clusters"]["east"]["state"] == "stale"
            assert r["clusters"]["east"]["age_s"] == pytest.approx(8.0)
            assert r["degraded"] is True
            # Counted — at its last VERIFIED generation, still bit-exact.
            assert r["per_cluster"]["east"] == _oracle_totals(snaps["east"])
            assert r["excluded"] == []

    def test_lost_cluster_excluded_and_named_never_silently_summed(self):
        now = [0.0]
        with FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, clock=lambda: now[0]
        ) as fed:
            snaps = _cluster_snaps("reference")
            for name, snap in snaps.items():
                fed.inject(name, snap)
            now[0] = 30.0
            for name, snap in snaps.items():
                if name != "east":
                    fed.inject(name, snap)
            r = fed.dispatch({"op": "fed_sweep", **GRID})
            assert r["excluded"] == ["east"]
            assert "east" not in r["per_cluster"]
            assert r["clusters"]["east"]["state"] == "lost"
            survivors = [
                sum(r["per_cluster"][n][s] for n in ("west", "north"))
                for s in range(len(CPU))
            ]
            assert r["totals"] == survivors

    def test_fed_rank_headroom_and_costs(self):
        with FederationServer(stale_after_s=5.0, evict_after_s=20.0) as fed:
            snaps = _cluster_snaps("reference")
            for name, snap in snaps.items():
                fed.inject(name, snap)
            r = fed.dispatch(
                {"op": "fed_rank", "cpuRequests": "500m",
                 "memRequests": "500mb", "replicas": "4"}
            )
            totals = [row["total"] for row in r["ranking"]]
            assert totals == sorted(totals, reverse=True)
            assert [row["rank"] for row in r["ranking"]] == [1, 2, 3]
            # A costs map reorders the FITTING clusters cheapest-first
            # (an un-costed cluster ranks after every costed one).
            by_headroom = [row["cluster"] for row in r["ranking"]]
            costs = {by_headroom[0]: 9.0, by_headroom[2]: 0.1}
            r2 = fed.dispatch(
                {"op": "fed_rank", "cpuRequests": "500m",
                 "memRequests": "500mb", "replicas": "4", "costs": costs}
            )
            assert [row["cluster"] for row in r2["ranking"]] == [
                by_headroom[2], by_headroom[0], by_headroom[1]
            ]

    def test_fed_rank_rejects_multi_scenario(self):
        with FederationServer(stale_after_s=5.0, evict_after_s=20.0) as fed:
            fed.inject("c", synthetic_snapshot(8, seed=5))
            with pytest.raises(ValueError, match="one scenario"):
                fed.dispatch({"op": "fed_rank", **GRID})

    def test_spillover_demand_and_greedy_fill(self):
        with FederationServer(stale_after_s=5.0, evict_after_s=20.0) as fed:
            snaps = _cluster_snaps("reference")
            for name, snap in snaps.items():
                fed.inject(name, snap)
            r = fed.dispatch(
                {"op": "spillover", "cluster": "east",
                 "cpuRequests": "500m", "memRequests": "500mb"}
            )
            assert r["demand"] == int(snaps["east"].pods_count.sum())
            placed = sum(p["replicas"] for p in r["placements"])
            assert placed + r["unplaced"] == r["demand"]
            assert r["absorbed"] == (r["unplaced"] == 0)
            # Greedy, most headroom first; no placement exceeds headroom.
            headrooms = [p["headroom"] for p in r["placements"]]
            assert headrooms == sorted(headrooms, reverse=True)
            for p in r["placements"]:
                assert 0 <= p["replicas"] <= max(p["headroom"], 0)
            # Explicit demand override.
            r2 = fed.dispatch(
                {"op": "spillover", "cluster": "east", "demand": 1,
                 "cpuRequests": "500m", "memRequests": "500mb"}
            )
            assert r2["demand"] == 1 and r2["absorbed"]

    def test_spillover_of_lost_cluster_is_typed_refusal(self):
        now = [0.0]
        with FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, clock=lambda: now[0]
        ) as fed:
            snaps = _cluster_snaps("reference")
            for name, snap in snaps.items():
                fed.inject(name, snap)
            now[0] = 30.0
            for name, snap in snaps.items():
                if name != "east":
                    fed.inject(name, snap)
            with pytest.raises(ClusterLostError, match="east"):
                fed.dispatch({"op": "spillover", "cluster": "east"})
            with pytest.raises(FederationError, match="unknown"):
                fed.dispatch({"op": "spillover", "cluster": "nowhere"})

    def test_concat_matches_members_and_single_passthrough(self):
        snaps = list(_cluster_snaps("strict").values())
        combined = concat_snapshots(snaps)
        assert combined.n_nodes == sum(s.n_nodes for s in snaps)
        assert combined.semantics == "strict"
        assert concat_snapshots([snaps[0]]) is snaps[0]
        # Taints concatenate positionally (the implicit-mask input).
        off = snaps[0].n_nodes
        assert combined.taints[off + 1] == snaps[1].taints[1]

    def test_all_lost_fleet_answers_zero_with_everything_named(self):
        now = [0.0]
        with FederationServer(
            stale_after_s=1.0, evict_after_s=2.0, clock=lambda: now[0]
        ) as fed:
            fed.inject("a", synthetic_snapshot(8, seed=6))
            fed.inject("b", synthetic_snapshot(8, seed=7))
            now[0] = 10.0
            r = fed.dispatch({"op": "fed_sweep", **GRID})
            assert r["totals"] == [0] * len(CPU)
            assert sorted(r["excluded"]) == ["a", "b"]
            assert r["per_cluster"] == {}


# ---------------------------------------------------------------------------
# The wire chaos suite: 3 leaders behind seeded fault proxies
# ---------------------------------------------------------------------------
class _Fleet:
    """3 cluster leaders, each behind a stream-mode fault proxy, one
    FederationServer subscribed through the proxies on an injected
    clock, and a wire client — torn down in reverse."""

    def __init__(self, semantics, *, plans=None, stale=2.0, evict=6.0):
        self.now = [0.0]
        self.names = ("east", "west", "north")
        self.snaps = _cluster_snaps(semantics)
        self.leaders = {}
        self.pubs = {}
        self.proxies = {}
        self.oracle = {}  # (cluster, generation) -> snapshot
        for name in self.names:
            pub = PlanePublisher(heartbeat_s=0.1)
            server = CapacityServer(
                self.snaps[name], port=0, plane=pub, batch_window_ms=0.0
            )
            server.start()
            plan = (plans or {}).get(name) or FaultPlan([])
            proxy = FaultProxy(pub.address, plan, stream=True).start()
            self.leaders[name], self.pubs[name] = server, pub
            self.proxies[name] = proxy
            self.oracle[(name, server.generation)] = self.snaps[name]
        self.fed = FederationServer(
            {n: self.proxies[n].address for n in self.names},
            stale_after_s=stale,
            evict_after_s=evict,
            clock=lambda: self.now[0],
            seed=7,
        ).start()
        self.client = CapacityClient(*self.fed.address)

    def publish(self, name, snap):
        self.leaders[name].replace_snapshot(snap)
        self.oracle[(name, self.leaders[name].generation)] = snap

    def wait_state(self, want, timeout_s=15.0):
        def ok():
            states = {
                n: c["state"]
                for n, c in self.fed.status()["clusters"].items()
            }
            return states == want

        _wait_for(ok, timeout_s=timeout_s, what=f"states {want}")

    def wait_generation(self, name, generation, timeout_s=15.0):
        _wait_for(
            lambda: self.fed.status()["clusters"][name]["generation"]
            >= generation,
            timeout_s=timeout_s,
            what=f"{name} at generation {generation}",
        )

    def close(self):
        self.client.close()
        self.fed.close()
        for name in self.names:
            self.proxies[name].stop()
            self.pubs[name].close()
            self.leaders[name].shutdown()


def _assert_reply_exact(fleet, reply, *, exclude=()):
    """Every per-cluster row bit-identical to the sequential oracle at
    the STAMPED generation, grand totals exactly their sum, lost
    clusters named — the zero-silently-wrong-totals pin."""
    grand = [0] * len(CPU)
    for name, totals in reply["per_cluster"].items():
        gen = reply["clusters"][name]["generation"]
        snap = fleet.oracle[(name, gen)]
        want = _oracle_totals(snap)
        assert totals == want, (name, gen)
        grand = [g + w for g, w in zip(grand, want)]
    assert reply["totals"] == grand
    assert sorted(reply["excluded"]) == sorted(exclude)
    for name in exclude:
        assert name not in reply["per_cluster"]


@pytest.mark.parametrize("semantics", ["reference", "strict"])
def test_partition_stale_lost_heal_contract(semantics):
    """THE acceptance test: seeded partition of 1-of-3 clusters mid-run;
    fresh clusters bit-exact throughout, the partitioned one explicitly
    stale (bounded age) → lost (excluded, named) → fresh after heal;
    per-cluster watermarks monotone across every reply."""
    fleet = _Fleet(semantics)
    watermarks = {n: 0 for n in fleet.names}

    def query():
        r = fleet.client.fed_sweep(**GRID)
        for n, entry in r["clusters"].items():
            assert entry["generation"] >= watermarks[n], (
                f"{n} watermark regressed: "
                f"{entry['generation']} < {watermarks[n]}"
            )
            watermarks[n] = entry["generation"]
        return r

    try:
        fleet.wait_state({n: "fresh" for n in fleet.names})
        r = query()
        _assert_reply_exact(fleet, r)

        # Churn: every leader publishes a derived generation; the
        # federation converges and answers stay exact.
        for i, name in enumerate(fleet.names):
            fleet.publish(name, _mutate(fleet.snaps[name], seed=60 + i))
        for name in fleet.names:
            fleet.wait_generation(name, 2)
        r = query()
        _assert_reply_exact(fleet, r)
        assert all(
            r["clusters"][n]["generation"] >= 2 for n in fleet.names
        )

        # PARTITION east mid-run (runtime control, no proxy restart).
        fleet.proxies["east"].partition("both")
        fleet.now[0] = 3.0  # past stale (2), inside evict (6)
        # The survivors' heartbeats re-verify them at the advanced
        # clock; east can only age.
        fleet.wait_state(
            {"east": "stale", "west": "fresh", "north": "fresh"}
        )
        r = query()
        east = r["clusters"]["east"]
        assert east["state"] == "stale"
        assert 2.0 < east["age_s"] <= 6.0  # bounded, explicit
        assert r["degraded"] is True
        _assert_reply_exact(fleet, r)  # stale view still exact at its gen
        assert fleet.proxies["east"].partition_dropped > 0

        # A generation east publishes DURING the partition must not
        # appear anywhere (nothing crossed the cut).
        fleet.publish("east", _mutate(fleet.snaps["east"], seed=99))
        r = query()
        assert r["clusters"]["east"]["generation"] == watermarks["east"]

        # Past the eviction horizon: lost, excluded, named.
        fleet.now[0] = 7.0
        fleet.wait_state(
            {"east": "lost", "west": "fresh", "north": "fresh"}
        )
        assert not fleet.fed.healthy()
        r = query()
        _assert_reply_exact(fleet, r, exclude=["east"])

        # HEAL: resubscription resumes (checkpoint: east moved on while
        # partitioned) and east serves fresh again — with the
        # partition-era generation finally visible, watermark advanced,
        # never regressed.
        fleet.proxies["east"].heal()
        fleet.wait_state(
            {"east": "fresh", "west": "fresh", "north": "fresh"}
        )
        fleet.wait_generation("east", 3)
        r = query()
        _assert_reply_exact(fleet, r)
        assert r["clusters"]["east"]["generation"] >= 3
        assert fleet.fed.healthy()
    finally:
        fleet.close()


def test_garbled_streams_never_misapply():
    """Seeded garbage/gap faults on every leader link: the digest chain
    refuses every corrupted frame, resyncs, and every reply stays
    bit-exact at its stamped generations."""
    plans = {
        name: FaultPlan.seeded(
            1000 + i, 40, fault_rate=0.3, faults=("garbage", "drop_pre")
        )
        for i, name in enumerate(("east", "west", "north"))
    }
    fleet = _Fleet("reference", plans=plans, stale=8.0, evict=30.0)
    try:
        fleet.wait_state({n: "fresh" for n in fleet.names})
        for round_i in range(4):
            for i, name in enumerate(fleet.names):
                fleet.publish(
                    name,
                    _mutate(fleet.snaps[name], seed=200 + 10 * round_i + i),
                )
            for name in fleet.names:
                fleet.wait_generation(name, 2 + round_i)
            r = fleet.client.fed_sweep(**GRID)
            _assert_reply_exact(fleet, r)
        injected = sum(
            sum(p.plan.injected.values()) for p in fleet.proxies.values()
        )
        assert injected > 0, "the chaos plan never fired — vacuous test"
    finally:
        fleet.close()


def test_asymmetric_partition_one_way_drop():
    """to_client: the leader still hears the subscriber (hello crosses)
    but no frame ever returns — the cluster goes stale exactly like a
    symmetric cut, then heals."""
    fleet = _Fleet("reference", stale=2.0, evict=30.0)
    try:
        fleet.wait_state({n: "fresh" for n in fleet.names})
        fleet.proxies["west"].partition("to_client")
        fleet.now[0] = 3.0
        fleet.wait_state(
            {"east": "fresh", "west": "stale", "north": "fresh"}
        )
        assert fleet.proxies["west"].partition_dropped > 0
        fleet.proxies["west"].heal()
        fleet.wait_state({n: "fresh" for n in fleet.names})
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# The verified-age accessors (satellite)
# ---------------------------------------------------------------------------
class TestSubscriberVerifiedAge:
    def test_heartbeats_keep_verified_age_bounded(self):
        now = [0.0]
        snap = synthetic_snapshot(8, seed=8)
        pub = PlanePublisher(heartbeat_s=0.05)
        leader = CapacityServer(snap, port=0, plane=pub, batch_window_ms=0.0)
        leader.start()
        replica = CapacityServer(snap, port=0, batch_window_ms=0.0)
        replica.start()
        sub = PlaneSubscriber(
            pub.address, replica, stale_after_s=30.0, clock=lambda: now[0]
        )
        try:
            _wait_for(
                lambda: sub.last_verified_age_s() is not None,
                what="first verification",
            )
            now[0] = 50.0
            # The next heartbeat (stamped with the HELD generation)
            # re-verifies at the advanced clock.
            _wait_for(
                lambda: sub.last_verified_age_s() == pytest.approx(0.0),
                what="heartbeat re-verification",
            )
            # Leader gone: the verified age can only grow.
            pub.close()
            leader.shutdown()
            time.sleep(0.2)
            now[0] = 60.0
            age = sub.last_verified_age_s()
            assert age is not None and age >= 10.0
        finally:
            sub.stop()
            replica.shutdown()
            pub.close()
            leader.shutdown()

    def test_subscriber_stats_shape_pinned(self):
        """The stats() dict is a wire/ops surface — the verified-age
        accessor rides separately, and this shape must not drift."""
        snap = synthetic_snapshot(4, seed=9)
        server = CapacityServer(snap, port=0, batch_window_ms=0.0)
        sub = PlaneSubscriber(("127.0.0.1", 1), server, stale_after_s=1.0)
        try:
            assert set(sub.stats().keys()) == {
                "role", "leader", "generation", "digest", "applied",
                "skipped", "resyncs", "errors", "leader_draining",
                "sync_age_s", "stale", "stale_after_s", "last_error",
            }
        finally:
            sub.stop()
            server.shutdown()


class TestFollowerVerifiedAge:
    def _follower(self, clock):
        from kubernetesclustercapacity_tpu.fixtures import synthetic_fixture
        from kubernetesclustercapacity_tpu.follower import ClusterFollower
        from kubernetesclustercapacity_tpu.kubeapi import (
            KubeClient,
            KubeConfig,
        )

        from test_kubeapi import MockApiserver

        fixture = synthetic_fixture(4, seed=41, unhealthy_frac=0.0)
        server = MockApiserver(fixture, require_token="tok")
        cfg = KubeConfig(f"http://127.0.0.1:{server.port}", token="tok")
        follower = ClusterFollower(
            client_factory=lambda: KubeClient(cfg),
            stop_on_idle_window=True,
            clock=clock,
        )
        return follower, server

    def test_last_verified_age_uses_injected_clock(self):
        now = [10.0]
        follower, server = self._follower(lambda: now[0])
        try:
            assert follower.last_verified_age_s() is None
            follower.start(watch=False)
            assert follower.last_verified_age_s() == pytest.approx(0.0)
            now[0] = 17.0
            assert follower.last_verified_age_s() == pytest.approx(7.0)
            assert follower.last_relist_age_s() == pytest.approx(7.0)
        finally:
            follower.stop()
            server.close()

    def test_follower_stats_shape_pinned(self):
        """Regression pin: the stats() dict shape is a wire surface
        (info op / doctor); the new accessor must NOT widen it."""
        now = [0.0]
        follower, server = self._follower(lambda: now[0])
        try:
            assert set(follower.stats().keys()) == {
                "relists", "relist_failures", "watch_failures",
                "events_applied", "backoff_s", "recent_errors",
                "pdb_unavailable", "fatal",
            }
        finally:
            follower.stop()
            server.close()


# ---------------------------------------------------------------------------
# ReplicaSet over federation endpoints (satellite)
# ---------------------------------------------------------------------------
class TestReplicaSetFederation:
    def _two_feds(self):
        """fed_a holds 'east' LOST (aged out); fed_b holds it fresh."""
        now_a = [100.0]
        fed_a = FederationServer(
            stale_after_s=1.0, evict_after_s=2.0, clock=lambda: now_a[0]
        )
        fed_b = FederationServer(stale_after_s=30.0, evict_after_s=60.0)
        snap = synthetic_snapshot(16, seed=70)
        fed_a.inject("east", snap, generation=4)
        now_a[0] = 110.0  # east aged past fed_a's horizon: lost
        fed_b.inject("east", snap, generation=4)
        fed_a.start()
        fed_b.start()
        return fed_a, fed_b

    def test_cluster_lost_wire_code_is_typed(self):
        fed_a, fed_b = self._two_feds()
        try:
            with CapacityClient(*fed_a.address) as c:
                with pytest.raises(ClusterLostError):
                    c.spillover("east")
        finally:
            fed_a.close()
            fed_b.close()

    def test_probe_demotes_lost_endpoint_and_call_fails_over(self):
        fed_a, fed_b = self._two_feds()
        rs = ReplicaSet(
            [fed_a.address, fed_b.address], cluster="east", rounds=2
        )
        try:
            probe = rs.probe()
            assert probe[0]["cluster_state"] == "lost"
            assert probe[1]["cluster_state"] == "fresh"
            stats = rs.stats()
            assert stats["endpoints"][0]["lost"] is True
            assert stats["endpoints"][1]["lost"] is False
            # Demoted like draining: the healthy endpoint rotates first.
            assert rs._rotation()[0].name == rs.endpoints[1]
            r = rs.call("spillover", cluster="east")
            assert r["cluster"] == "east"  # answered by fed_b
        finally:
            rs.close()
            fed_a.close()
            fed_b.close()

    def test_midcall_cluster_lost_refusal_marks_endpoint(self):
        fed_a, fed_b = self._two_feds()
        rs = ReplicaSet(
            [fed_a.address, fed_b.address], cluster="east", rounds=2
        )
        try:
            # No probe: the first call hits fed_a, takes the typed
            # refusal, marks it lost, and retries elsewhere.
            r = rs.call("spillover", cluster="east")
            assert r["cluster"] == "east"
            assert rs.stats()["endpoints"][0]["lost"] is True
        finally:
            rs.close()
            fed_a.close()
            fed_b.close()


# ---------------------------------------------------------------------------
# Surfaces: client wrappers, CLI, reports, metrics, doctor
# ---------------------------------------------------------------------------
class TestFedSurfaces:
    @pytest.fixture()
    def fed_wire(self):
        now = [0.0]
        fed = FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, clock=lambda: now[0]
        )
        snaps = _cluster_snaps("reference")
        for i, (name, snap) in enumerate(snaps.items()):
            fed.inject(name, snap, generation=i + 1)
        fed.start()
        yield fed, snaps, now
        fed.close()

    def test_client_wrappers_round_trip(self, fed_wire):
        fed, snaps, _now = fed_wire
        with CapacityClient(*fed.address) as c:
            status = c.fed_status()
            assert status["enabled"] and status["healthy"]
            assert status["counts"] == {
                "fresh": 3, "stale": 0, "lost": 0, "total": 3,
            }
            sweep = c.fed_sweep(
                cpu_request_milli=np.asarray(CPU),
                mem_request_bytes=np.asarray(MEM),
                replicas=np.asarray(REPS),
            )
            assert sweep["per_cluster"]["east"] == _oracle_totals(
                snaps["east"]
            )
            rank = c.fed_rank(cpuRequests="500m", memRequests="500mb")
            assert len(rank["ranking"]) == 3
            spill = c.spillover("west", demand=2)
            assert spill["demand"] == 2
            info = c.info()
            assert info["capabilities"]["federation"] is True

    def test_auth_token_gates_every_op_but_ping(self):
        fed = FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, auth_token="sesame"
        )
        fed.inject("c", synthetic_snapshot(8, seed=11))
        fed.start()
        try:
            with CapacityClient(*fed.address) as c:
                assert c.ping() == "pong"
                with pytest.raises(RuntimeError, match="auth token"):
                    c.fed_status()
            with CapacityClient(*fed.address, token="sesame") as c:
                assert c.fed_status()["enabled"]
        finally:
            fed.close()

    def test_cli_fed_status_exit_codes_and_reports(self, fed_wire, capsys):
        from kubernetesclustercapacity_tpu import cli

        fed, snaps, now = fed_wire
        addr = f"127.0.0.1:{fed.address[1]}"
        assert cli.main(["-fed-status", addr]) == 0
        out = capsys.readouterr().out
        assert "fresh" in out and "verdict: ok" in out
        # JSON form parses and carries the vector.
        import json as _json

        assert cli.main(["-fed-status", addr, "-output", "json"]) == 0
        parsed = _json.loads(capsys.readouterr().out)
        assert set(parsed["clusters"]) == set(snaps)
        # A lost cluster flips the exit code (and is named).
        now[0] = 30.0
        for i, (name, snap) in enumerate(snaps.items()):
            if name != "east":
                fed.inject(name, snap, generation=10 + i)
        assert cli.main(["-fed-status", addr]) == 1
        out = capsys.readouterr().out
        assert "DEGRADED" in out and "east" in out

    def test_cli_fed_sweep_exit_codes(self, fed_wire, capsys):
        from kubernetesclustercapacity_tpu import cli

        fed, snaps, now = fed_wire
        addr = f"127.0.0.1:{fed.address[1]}"
        assert cli.main(["-fed-sweep", addr, "-cpuRequests", "100m",
                         "-memRequests", "100mb", "-replicas", "1"]) == 0
        out = capsys.readouterr().out
        assert "fleet totals" in out
        # An unschedulable scenario exits 1.
        assert cli.main(["-fed-sweep", addr, "-cpuRequests", "100m",
                         "-memRequests", "100mb",
                         "-replicas", "99999999"]) == 1
        capsys.readouterr()
        # A lost cluster exits 1 even when schedulable, and is named.
        now[0] = 30.0
        for i, (name, snap) in enumerate(snaps.items()):
            if name != "east":
                fed.inject(name, snap, generation=10 + i)
        assert cli.main(["-fed-sweep", addr, "-cpuRequests", "100m",
                         "-memRequests", "100mb", "-replicas", "1"]) == 1
        out = capsys.readouterr().out
        assert "EXCLUDED" in out and "east" in out

    def test_metrics_gauges_and_sweep_counter(self):
        now = [0.0]
        registry = MetricsRegistry()
        fed = FederationServer(
            stale_after_s=5.0, evict_after_s=20.0, clock=lambda: now[0],
            registry=registry,
        )
        try:
            fed.inject("east", synthetic_snapshot(8, seed=12), generation=3)
            fed.dispatch({"op": "fed_sweep", **GRID})
            fed.dispatch({"op": "fed_sweep", **GRID})
            snap = registry.snapshot()
            key = 'cluster="east"'
            assert snap["kccap_fed_cluster_up"]["values"][key] == 1.0
            assert snap["kccap_fed_generation"]["values"][key] == 3.0
            assert snap["kccap_fed_staleness_seconds"]["values"][
                key
            ] == pytest.approx(0.0)
            assert snap["kccap_fed_sweep_total"]["values"][""] == 2
            now[0] = 8.0
            snap = registry.snapshot()
            assert snap["kccap_fed_cluster_up"]["values"][key] == 0.0
            assert snap["kccap_fed_staleness_seconds"]["values"][
                key
            ] == pytest.approx(8.0)
        finally:
            fed.close()

    def test_doctor_federation_line(self, fed_wire):
        from kubernetesclustercapacity_tpu.utils.doctor import run_doctor

        fed, snaps, now = fed_wire
        out, code = run_doctor(
            backend_timeout_s=10.0,
            probe_code="print('DEVICES 0s D x1')",
            federation_addr=fed.address,
        )
        line = next(
            ln for ln in out.splitlines() if ln.startswith("federation")
        )
        assert "ok: 3 cluster(s)" in line and "fresh=3" in line
        assert code == 0
        now[0] = 30.0
        for i, (name, snap) in enumerate(snaps.items()):
            if name != "east":
                fed.inject(name, snap, generation=10 + i)
        out, code = run_doctor(
            backend_timeout_s=10.0,
            probe_code="print('DEVICES 0s D x1')",
            federation_addr=fed.address,
        )
        line = next(
            ln for ln in out.splitlines() if ln.startswith("federation")
        )
        assert "FAILED" in line and "east" in line
        assert code == 1
