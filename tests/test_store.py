"""Incremental store (informer analog) tests.

The load-bearing property: after ANY event stream, the store's snapshot is
element-identical to a full ``snapshot_from_fixture`` repack of its raw
state — under both semantics, including the reference quirks (phantom rows
re-homing orphan pods, mod-2^64 wrap, parse-fail→0).  Randomized event
streams drive that invariant; directed tests pin the interesting
transitions (health flips, node joins/leaves, orphan pods).
"""

import copy
import random

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.fixtures import synthetic_fixture
from kubernetesclustercapacity_tpu.oracle import ReferencePanic
from kubernetesclustercapacity_tpu.snapshot import snapshot_from_fixture
from kubernetesclustercapacity_tpu.store import ClusterStore, StoreError

_COLS = (
    "alloc_cpu_milli",
    "alloc_mem_bytes",
    "alloc_pods",
    "used_cpu_req_milli",
    "used_cpu_lim_milli",
    "used_mem_req_bytes",
    "used_mem_lim_bytes",
    "pods_count",
    "healthy",
)


def assert_matches_repack(store: ClusterStore):
    snap = store.snapshot()
    repack = snapshot_from_fixture(
        store.fixture_view(),
        semantics=store.semantics,
        extended_resources=store.extended_resources,
    )
    assert snap.names == repack.names
    assert snap.node_log == repack.node_log
    assert snap.pod_cpu_errs == repack.pod_cpu_errs
    for col in _COLS:
        np.testing.assert_array_equal(
            getattr(snap, col), getattr(repack, col), err_msg=col
        )
    assert sorted(snap.extended) == sorted(repack.extended)
    for r in snap.extended:
        np.testing.assert_array_equal(snap.extended[r][0], repack.extended[r][0])
        np.testing.assert_array_equal(snap.extended[r][1], repack.extended[r][1])


def _mk_pod(name, node, phase="Running", cpu="250m", mem="512Mi"):
    return {
        "name": name,
        "namespace": "default",
        "nodeName": node,
        "phase": phase,
        "containers": [
            {"resources": {"requests": {"cpu": cpu, "memory": mem},
                           "limits": {"cpu": cpu, "memory": mem}}}
        ],
    }


def _mk_node(name, cpu="8", mem="16777216Ki", healthy=True):
    conds = [
        {"type": t, "status": "False"}
        for t in ("OutOfDisk", "MemoryPressure", "DiskPressure", "PIDPressure")
    ] + [{"type": "Ready", "status": "True"}]
    if not healthy:
        conds[1]["status"] = "True"
    return {
        "name": name,
        "allocatable": {"cpu": cpu, "memory": mem, "pods": "110"},
        "conditions": conds,
        "labels": {"kubernetes.io/hostname": name},
        "taints": [],
    }


class TestDirected:
    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    def test_pod_lifecycle(self, semantics):
        fx = synthetic_fixture(6, seed=3)
        store = ClusterStore(fx, semantics=semantics)
        node = fx["nodes"][0]["name"]
        pod = _mk_pod("newpod", node)
        store.apply_event({"type": "ADDED", "kind": "Pod", "object": pod})
        assert_matches_repack(store)
        moved = dict(pod, nodeName=fx["nodes"][1]["name"])
        store.apply_event({"type": "MODIFIED", "kind": "Pod", "object": moved})
        assert_matches_repack(store)
        store.apply_event({"type": "MODIFIED", "kind": "Pod",
                           "object": dict(moved, phase="Succeeded")})
        assert_matches_repack(store)
        store.apply_event({"type": "DELETED", "kind": "Pod", "object": moved})
        assert_matches_repack(store)

    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    def test_node_join_leave_and_health_flip(self, semantics):
        fx = synthetic_fixture(5, seed=4, unhealthy_frac=0.0)
        store = ClusterStore(fx, semantics=semantics)
        store.apply_event(
            {"type": "ADDED", "kind": "Node", "object": _mk_node("joiner")}
        )
        assert_matches_repack(store)
        # A pod lands on the new node, then the node goes unhealthy: in
        # reference mode the row becomes a phantom and re-homes to the
        # orphan-pod set; in strict mode only the mask flips.
        store.apply_event(
            {"type": "ADDED", "kind": "Pod", "object": _mk_pod("p1", "joiner")}
        )
        assert_matches_repack(store)
        store.apply_event(
            {"type": "MODIFIED", "kind": "Node",
             "object": _mk_node("joiner", healthy=False)}
        )
        assert_matches_repack(store)
        store.apply_event(
            {"type": "DELETED", "kind": "Node", "object": {"name": "joiner"}}
        )
        assert_matches_repack(store)
        assert "joiner" not in [n["name"] for n in store.fixture_view()["nodes"]]

    def test_orphan_pod_touches_all_phantom_rows_reference(self):
        fx = synthetic_fixture(8, seed=5, unhealthy_frac=0.4)
        store = ClusterStore(fx, semantics="reference")
        n_phantom = int(np.sum(~store.snapshot().healthy))
        assert n_phantom >= 2  # seed chosen to yield several phantoms
        before = store.snapshot().pods_count.copy()
        store.apply_event(
            {"type": "ADDED", "kind": "Pod", "object": _mk_pod("orphan", "")}
        )
        after = store.snapshot().pods_count
        # Every phantom row counted the orphan; healthy rows untouched.
        assert int(np.sum(after - before)) == n_phantom
        assert_matches_repack(store)

    def test_strict_extended_resources_update(self):
        fx = synthetic_fixture(4, seed=6)
        fx["nodes"][0]["allocatable"]["nvidia.com/gpu"] = "8"
        store = ClusterStore(
            fx, semantics="strict", extended_resources=("nvidia.com/gpu",)
        )
        pod = _mk_pod("gpu-pod", fx["nodes"][0]["name"])
        pod["containers"][0]["resources"]["requests"]["nvidia.com/gpu"] = "3"
        store.apply_event({"type": "ADDED", "kind": "Pod", "object": pod})
        alloc, used = store.snapshot().extended["nvidia.com/gpu"]
        assert alloc[0] == 8 and used[0] == 3
        assert_matches_repack(store)

    def test_bad_events_raise_and_leave_state_intact(self):
        fx = synthetic_fixture(3, seed=7)
        store = ClusterStore(fx, semantics="reference")
        before = store.snapshot()
        node0 = fx["nodes"][0]["name"]
        existing = store.fixture_view()["pods"][0]
        for ev in (
            {"type": "BOGUS", "kind": "Pod", "object": _mk_pod("x", node0)},
            {"type": "ADDED", "kind": "Gizmo", "object": {}},
            {"type": "ADDED", "kind": "Pod", "object": existing},
            {"type": "DELETED", "kind": "Pod", "object": _mk_pod("ghost", node0)},
            {"type": "MODIFIED", "kind": "Node", "object": _mk_node("ghost")},
            {"type": "ADDED", "kind": "Node", "object": fx["nodes"][0]},
        ):
            with pytest.raises(StoreError):
                store.apply_event(ev)
        after = store.snapshot()
        for col in _COLS:
            np.testing.assert_array_equal(
                getattr(before, col), getattr(after, col)
            )

    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    def test_malformed_objects_rejected_without_poisoning(self, semantics):
        """A malformed object must never enter raw state: later events on
        the same node and the full-repack invariant must keep working."""
        fx = synthetic_fixture(4, seed=11)
        store = ClusterStore(fx, semantics=semantics)
        node0 = fx["nodes"][0]["name"]
        bad_objects = [
            {"name": "bad", "namespace": "d", "nodeName": node0,
             "phase": "Running", "containers": "oops"},
            {"name": "bad", "namespace": "d", "nodeName": node0,
             "phase": "Running",
             "containers": [{"resources": {"requests": 7}}]},
            {"name": ["unhashable"], "namespace": "d", "nodeName": node0,
             "phase": "Running", "containers": []},
            {"name": "bad", "namespace": "d", "nodeName": {},
             "phase": "Running", "containers": []},
        ]
        for obj in bad_objects:
            with pytest.raises(StoreError, match="malformed pod"):
                store.apply_event({"type": "ADDED", "kind": "Pod", "object": obj})
        with pytest.raises(StoreError, match="malformed node"):
            store.apply_event({"type": "ADDED", "kind": "Node",
                               "object": {"name": "badnode",
                                          "allocatable": "oops",
                                          "conditions": []}})
        # The store still works: a good event on the same node applies and
        # the state is still repackable.
        store.apply_event(
            {"type": "ADDED", "kind": "Pod", "object": _mk_pod("good", node0)}
        )
        assert_matches_repack(store)
        assert "bad" not in [p["name"] for p in store.fixture_view()["pods"]]

    def test_reference_panic_node_is_rejected_without_mutation(self):
        store = ClusterStore(synthetic_fixture(3, seed=8), semantics="reference")
        bad = _mk_node("short-conds")
        bad["conditions"] = bad["conditions"][:2]  # <4 → reference panic (Q3)
        with pytest.raises(ReferencePanic):
            store.apply_event({"type": "ADDED", "kind": "Node", "object": bad})
        assert store.n_nodes == 3
        assert_matches_repack(store)

    def test_events_do_not_alias_caller_objects(self):
        fx = synthetic_fixture(3, seed=9)
        store = ClusterStore(fx, semantics="strict")
        pod = _mk_pod("aliased", fx["nodes"][0]["name"])
        store.apply_event({"type": "ADDED", "kind": "Pod", "object": pod})
        pod["containers"][0]["resources"]["requests"]["cpu"] = "4000"
        assert_matches_repack(store)  # mutation above must not leak in
        fx["nodes"][0]["allocatable"]["cpu"] = "999"
        assert_matches_repack(store)


class TestRandomizedInvariant:
    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_event_stream_matches_repack(self, semantics, seed):
        rng = random.Random(seed)
        fx = synthetic_fixture(
            10, seed=seed, unhealthy_frac=0.2, unscheduled_running_pods=2
        )
        store = ClusterStore(fx, semantics=semantics)
        pod_serial = 0
        for step in range(60):
            live = store.fixture_view()
            node_names = [n["name"] for n in live["nodes"]]
            roll = rng.random()
            if roll < 0.35 or not live["pods"]:
                pod_serial += 1
                target = rng.choice(node_names + ["", "nowhere"])
                phase = rng.choice(["Running", "Pending", "Succeeded"])
                ev = {"type": "ADDED", "kind": "Pod",
                      "object": _mk_pod(f"r{seed}-{pod_serial}", target,
                                        phase=phase,
                                        cpu=rng.choice(["100m", "1", "2"]),
                                        mem=rng.choice(["128Mi", "1Gi"]))}
            elif roll < 0.55:
                victim = copy.deepcopy(rng.choice(live["pods"]))
                ev = {"type": "DELETED", "kind": "Pod", "object": victim}
            elif roll < 0.75:
                victim = copy.deepcopy(rng.choice(live["pods"]))
                victim["nodeName"] = rng.choice(node_names + [""])
                victim["phase"] = rng.choice(["Running", "Failed", "Unknown"])
                ev = {"type": "MODIFIED", "kind": "Pod", "object": victim}
            elif roll < 0.85:
                ev = {"type": "ADDED", "kind": "Node",
                      "object": _mk_node(f"join-{seed}-{step}",
                                         healthy=rng.random() > 0.3)}
            elif roll < 0.95 and node_names:
                name = rng.choice(node_names)
                ev = {"type": "MODIFIED", "kind": "Node",
                      "object": _mk_node(name, cpu=rng.choice(["4", "16"]),
                                         healthy=rng.random() > 0.3)}
            else:
                ev = {"type": "DELETED", "kind": "Node",
                      "object": {"name": rng.choice(node_names)}}
            store.apply_event(ev)
            if step % 10 == 9:
                assert_matches_repack(store)
        assert_matches_repack(store)


class TestStrictNameValidation:
    """Strict mode matches pods to rows by name: duplicate/empty names would
    diverge from _pack_strict's last-wins index, so they are rejected
    up front (reference mode keeps its phantom-row quirks)."""

    def test_duplicate_node_names_rejected_in_strict(self):
        fixture = {"nodes": [_mk_node("twin"), _mk_node("twin")], "pods": []}
        with pytest.raises(StoreError, match="duplicate node names"):
            ClusterStore(fixture, semantics="strict")

    def test_empty_node_name_rejected_in_strict(self):
        fixture = {"nodes": [{**_mk_node("x"), "name": ""}], "pods": []}
        with pytest.raises(StoreError, match="non-empty"):
            ClusterStore(fixture, semantics="strict")

    def test_strict_added_event_empty_name_rejected(self):
        store = ClusterStore(
            {"nodes": [_mk_node("a")], "pods": []}, semantics="strict"
        )
        anon = {**_mk_node("y"), "name": ""}
        with pytest.raises(StoreError, match="non-empty"):
            store.apply_event(
                {"type": "ADDED", "kind": "Node", "object": anon}
            )
        assert_matches_repack(store)  # rejected pre-mutation

    def test_reference_mode_still_accepts_duplicates(self):
        fixture = {"nodes": [_mk_node("twin"), _mk_node("twin")], "pods": []}
        store = ClusterStore(fixture, semantics="reference")
        assert store.n_nodes == 2
        assert_matches_repack(store)


class TestScaleAndIndices:
    """The O(1)-index + amortized-growth paths (round-4 churn fix)."""

    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    def test_many_adds_then_deletes_match_repack(self, semantics):
        # Growth crosses several capacity doublings; deletes compact and
        # rebuild the inverted indices; the repack invariant must hold
        # throughout.
        fx = synthetic_fixture(3, seed=5)
        store = ClusterStore(fx, semantics=semantics)
        for k in range(70):
            store.apply_event(
                {"type": "ADDED", "kind": "Node",
                 "object": _mk_node(f"grow-{k}", healthy=(k % 3 != 0))}
            )
            if k % 10 == 9:
                assert_matches_repack(store)
        for k in range(0, 70, 2):
            store.apply_event(
                {"type": "DELETED", "kind": "Node",
                 "object": {"name": f"grow-{k}"}}
            )
        assert_matches_repack(store)
        # Post-compaction, pod events must land on the re-indexed rows.
        store.apply_event(
            {"type": "ADDED", "kind": "Pod",
             "object": _mk_pod("late", "grow-1")}
        )
        assert_matches_repack(store)
        assert store.has_node("grow-1") and not store.has_node("grow-0")

    def test_health_flip_moves_view_index_reference(self):
        # A reference-mode health flip changes the row's view name ("" for
        # phantom): pod matching must follow the flip through the index.
        fx = {"nodes": [_mk_node("flip")], "pods": []}
        store = ClusterStore(fx, semantics="reference")
        store.apply_event(
            {"type": "MODIFIED", "kind": "Node",
             "object": _mk_node("flip", healthy=False)}
        )
        # Now phantom: an orphan pod (nodeName "") must touch the row.
        store.apply_event(
            {"type": "ADDED", "kind": "Pod", "object": _mk_pod("orphan", "")}
        )
        assert_matches_repack(store)
        assert store.snapshot().pods_count[0] == 1
        store.apply_event(
            {"type": "MODIFIED", "kind": "Node",
             "object": _mk_node("flip", healthy=True)}
        )
        assert_matches_repack(store)
        assert store.snapshot().pods_count[0] == 0


class TestMalformedAndExtremeObjects:
    """Validate-before-mutate holds for the cases a hostile/degenerate
    event can produce: unhashable phases reject PRE-mutation, int64-capped
    quantities (upstream semantics) flow through, and served snapshots
    never alias live raw state."""

    def test_unhashable_phase_rejected_pre_mutation(self):
        for semantics in ("reference", "strict"):
            fx = synthetic_fixture(3, seed=11)
            store = ClusterStore(fx, semantics=semantics)
            node0 = fx["nodes"][0]["name"]
            bad = _mk_pod("bad-phase", node0)
            bad["phase"] = ["Running"]  # unhashable
            with pytest.raises(StoreError, match="malformed pod"):
                store.apply_event(
                    {"type": "ADDED", "kind": "Pod", "object": bad}
                )
            assert not store.has_pod("default", "bad-phase")
            assert_matches_repack(store)

    def test_capped_quantity_node_matches_repack(self):
        # '16E' exceeds int64; upstream Quantity caps at MaxInt64 — the
        # store must accept it and stay element-identical to a repack
        # (this once crashed with OverflowError AFTER mutating raw state).
        fx = synthetic_fixture(3, seed=12)
        store = ClusterStore(fx, semantics="strict")
        big = _mk_node("huge-mem")
        big["allocatable"]["memory"] = "16E"
        store.apply_event({"type": "ADDED", "kind": "Node", "object": big})
        snap = store.snapshot()
        i = snap.names.index("huge-mem")
        assert int(snap.alloc_mem_bytes[i]) == (1 << 63) - 1
        assert_matches_repack(store)

    def test_snapshot_labels_do_not_alias_store(self):
        fx = synthetic_fixture(3, seed=13)
        store = ClusterStore(fx, semantics="reference")
        snap = store.snapshot()
        snap.labels[0]["mutated"] = "yes"
        if snap.taints[0]:
            snap.taints[0][0]["mutated"] = "yes"
        assert "mutated" not in store.fixture_view()["nodes"][0]["labels"]
        assert_matches_repack(store)
        # Provenance entries are immutable tuples: a caller cannot append
        # into the store's live per-row state at all.
        assert not hasattr(snap.pod_cpu_errs[0], "append")
        assert not hasattr(snap.node_log, "append") or isinstance(
            snap.node_log, list
        )  # the outer log list is a fresh copy; entries are tuples
        if snap.node_log:
            assert isinstance(snap.node_log[0], tuple)

    def test_transcript_provenance_survives_updates(self):
        # A store-served reference snapshot must replay the same skip and
        # codec-error lines a fresh pack would — including after events.
        fx = synthetic_fixture(6, seed=14, unhealthy_frac=0.5)
        fx["nodes"][0]["allocatable"]["cpu"] = "4.5"  # codec error line
        store = ClusterStore(fx, semantics="reference")
        assert_matches_repack(store)  # node_log/pod_cpu_errs included
        node0 = fx["nodes"][1]["name"]
        weird = _mk_pod("weird-cpu", node0, cpu="bogus")
        store.apply_event({"type": "ADDED", "kind": "Pod", "object": weird})
        snap = store.snapshot()
        assert any(k == "cpu_err" for k, _ in snap.node_log)
        assert any("bogus" in errs for errs in snap.pod_cpu_errs)
        assert_matches_repack(store)


class TestIsolationBarrier:
    """The fast deep-copier must keep the store's aliasing barrier."""

    def test_cyclic_event_object_raises_store_error(self):
        from kubernetesclustercapacity_tpu.store import ClusterStore, StoreError

        store = ClusterStore({"nodes": [], "pods": []})
        obj = {"namespace": "d", "name": "p"}
        obj["self"] = obj
        import pytest as _pytest

        with _pytest.raises(StoreError, match="cyclic"):
            store.apply_event(
                {"type": "ADDED", "kind": "Pod", "object": obj}
            )

    def test_applied_object_does_not_alias_caller(self):
        from kubernetesclustercapacity_tpu.fixtures import synthetic_fixture
        from kubernetesclustercapacity_tpu.store import ClusterStore

        fx = synthetic_fixture(3, seed=9, unhealthy_frac=0.0)
        store = ClusterStore(fx, semantics="reference")
        pod = dict(fx["pods"][0], namespace="iso", name="iso-pod")
        ev = {"type": "ADDED", "kind": "Pod", "object": pod}
        store.apply_event(ev)
        before = store.snapshot()
        # Caller mutates its object after apply: the store must not see it.
        pod["containers"][0]["resources"]["requests"]["cpu"] = "999999m"
        after = store.snapshot()
        assert (
            before.used_cpu_req_milli.tolist()
            == after.used_cpu_req_milli.tolist()
        )
