"""The forecast layers against their independent oracles: the Theil–Sen
trend vs a scalar-statistics comparator, the one-dispatch `[H×S]`
horizon sweep vs the pure-numpy seed-replay oracle (both semantics, all
four GROUPING×DEVCACHE kernel paths), and the catalog planner's
cannot-lie certification with its LP bound and drain dual."""

import dataclasses

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.audit.log import AuditLog
from kubernetesclustercapacity_tpu.forecast import (
    CatalogShape,
    PlannerError,
    apply_plan,
    fit_trend,
    horizon_oracle,
    parse_catalog,
    plan_capacity,
    project_horizon,
    trend_from_audit,
    trend_oracle,
)
from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot
from kubernetesclustercapacity_tpu.stochastic import (
    InsufficientHistoryError,
    extract_series,
    parse_stochastic_spec,
)
from kubernetesclustercapacity_tpu.timeline.watchlist import (
    WatchError,
    parse_watchlist,
)

USAGE = {
    "cpu": {"dist": "normal", "mean": "500m", "std": "150m"},
    "memory": {"dist": "lognormal", "mean": "1gb", "sigma": 0.4},
}

CATALOG = {
    "shapes": [
        {"name": "small", "cpu": "4", "memory": "16gb", "pods": 110,
         "unit_cost": 1.0},
        {"name": "big", "cpu": "16", "memory": "128gb", "pods": 250,
         "unit_cost": 6.5},
    ]
}


def _spec(**over):
    doc = {
        "usage": USAGE,
        "replicas": 40,
        "samples": 32,
        "seed": 7,
        "confidence": 0.95,
        **over,
    }
    return parse_stochastic_spec(doc)


def _fits_close(a, b):
    assert a.n == b.n
    assert a.slope_per_s == pytest.approx(b.slope_per_s, rel=1e-12, abs=1e-12)
    assert a.intercept == pytest.approx(b.intercept, rel=1e-12, abs=1e-9)
    assert a.mad == pytest.approx(b.mad, rel=1e-12, abs=1e-9)


class TestTrendFit:
    def test_exact_linear_series(self):
        t = np.arange(12, dtype=np.float64) * 60.0
        y = 100.0 + 2.5 * t
        fit = fit_trend(t, y)
        assert fit.slope_per_s == pytest.approx(2.5)
        assert fit.intercept == pytest.approx(100.0)
        assert fit.mad == pytest.approx(0.0)
        assert fit.level == pytest.approx(y[-1])
        assert fit.value_at(0.0) == pytest.approx(100.0)

    @pytest.mark.parametrize("shape", ["flat", "linear", "step", "noisy"])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_scalar_oracle(self, shape, seed):
        rng = np.random.default_rng(seed * 100 + hash(shape) % 97)
        n = int(rng.integers(3, 40))
        t = np.cumsum(rng.uniform(1.0, 120.0, size=n))
        if shape == "flat":
            y = np.full(n, float(rng.uniform(10, 1000)))
        elif shape == "linear":
            y = rng.uniform(-5, 5) * t + rng.uniform(0, 100)
        elif shape == "step":
            y = np.where(t > t[n // 2], 500.0, 100.0)
        else:
            y = 50.0 + 0.3 * t + rng.normal(0, 20, size=n)
        _fits_close(fit_trend(t, y), trend_oracle(t, y))

    def test_outlier_robustness(self):
        # Theil–Sen shrugs off a single spiked observation that would
        # wreck least squares — and still agrees with the oracle.
        t = np.arange(21, dtype=np.float64) * 30.0
        y = 10.0 + 1.0 * t
        y[10] += 1e6
        fit = fit_trend(t, y)
        _fits_close(fit, trend_oracle(t, y))
        assert fit.slope_per_s == pytest.approx(1.0, rel=0.05)

    def test_insufficient_and_bad_axes(self):
        with pytest.raises(InsufficientHistoryError):
            fit_trend([0.0], [1.0])
        with pytest.raises(InsufficientHistoryError):
            fit_trend([5.0, 5.0, 5.0], [1.0, 2.0, 3.0])  # zero span
        with pytest.raises(ValueError):
            fit_trend([2.0, 1.0], [1.0, 2.0])  # decreasing
        with pytest.raises(ValueError):
            fit_trend([[0.0, 1.0]], [1.0, 2.0])  # not 1-D

    def test_relative_slope_guards_nonpositive_level(self):
        t = np.arange(4, dtype=np.float64)
        fit = fit_trend(t, -10.0 - t)
        assert fit.level < 0
        assert fit.relative_slope_per_s == 0.0
        growing = fit_trend(t, 100.0 + 10.0 * t)
        assert growing.relative_slope_per_s == pytest.approx(
            10.0 / growing.level
        )


class TestSeriesFromAudit:
    def _audit_dir(self, tmp_path, *, ts_of=lambda g: 1000.0 + g * 60.0,
                   gens=6):
        d = str(tmp_path / "audit")
        base = synthetic_snapshot(10, seed=4)
        with AuditLog(d, checkpoint_every=3) as log:
            for g in range(1, gens + 1):
                snap = dataclasses.replace(
                    base,
                    used_cpu_req_milli=(
                        np.asarray(base.used_cpu_req_milli) + 50 * g
                    ).astype(np.int64),
                )
                log.record_generation(snap, g, ts=ts_of(g))
        return d, base

    def test_extract_series_totals_and_axis(self, tmp_path):
        d, base = self._audit_dir(tmp_path)
        s = extract_series(d, "cpu", "usage")
        assert not s.degraded_time_axis
        assert s.ts[0] == 1060.0 and s.ts[-1] == 1360.0
        base_total = int(np.asarray(base.used_cpu_req_milli).sum())
        expect = [base_total + 50 * 10 * g for g in range(1, 7)]
        assert s.totals.tolist() == [float(v) for v in expect]
        # Supply side is flat in this history.
        alloc = extract_series(d, "cpu", "allocatable")
        assert len(set(alloc.totals.tolist())) == 1

    def test_degraded_axis_falls_back_to_record_order(self, tmp_path):
        d, _ = self._audit_dir(tmp_path, ts_of=lambda g: 777.0)
        s = extract_series(d, "memory", "usage")
        assert s.degraded_time_axis
        assert s.ts.tolist() == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
        fit, series = trend_from_audit(d, "cpu", "usage")
        assert fit.degraded_time_axis and series.degraded_time_axis

    def test_trend_from_audit_matches_direct_fit(self, tmp_path):
        d, _ = self._audit_dir(tmp_path)
        fit, series = trend_from_audit(d, "cpu", "usage")
        _fits_close(fit, fit_trend(series.ts, series.totals))
        # 50 millicores per node per generation, 10 nodes, 60 s apart.
        assert fit.slope_per_s == pytest.approx(500.0 / 60.0)
        assert fit.relative_slope_per_s > 0

    def test_too_little_history_is_typed(self, tmp_path):
        d, _ = self._audit_dir(tmp_path, gens=2)
        with pytest.raises(InsufficientHistoryError):
            trend_from_audit(d, "cpu", "usage")
        with pytest.raises(ValueError):
            extract_series(d, "gpu", "usage")


class TestHorizon:
    @pytest.mark.parametrize("mode", ["reference", "strict"])
    def test_dispatch_matches_oracle(self, mode):
        snap = synthetic_snapshot(24, seed=9)
        spec = _spec(samples=24, seed=3)
        mask = implicit_taint_mask(snap)
        kw = dict(steps=6, step_s=1800.0, growth_cpu_per_s=2e-5,
                  growth_mem_per_s=1e-5, mode=mode, node_mask=mask)
        got = project_horizon(snap, spec, **kw)
        want = horizon_oracle(snap, spec, **kw)
        assert np.array_equal(got.totals, want.totals)
        for q in got.quantiles:
            assert got.quantiles[q].tolist() == want.quantiles[q].tolist()
            assert got.time_to_breach_s[q] == want.time_to_breach_s[q]

    def test_four_way_kernel_path_pin(self, monkeypatch):
        """GROUPING×DEVCACHE on/off answer bit-identically — the
        one-dispatch horizon grid takes every kernel path."""
        snap = synthetic_snapshot(32, seed=5)
        spec = _spec(samples=16, seed=8)
        results = []
        for grouping in ("1", "0"):
            for devcache in ("1", "0"):
                monkeypatch.setenv("KCCAP_GROUPING", grouping)
                monkeypatch.setenv("KCCAP_DEVCACHE", devcache)
                r = project_horizon(
                    snap, spec, steps=4, step_s=600.0,
                    growth_cpu_per_s=5e-5,
                )
                results.append(r.totals)
        for other in results[1:]:
            assert np.array_equal(results[0], other)

    def test_time_to_breach_closed_form(self):
        """Deterministic point usage on one fresh node: the breach step
        is pure arithmetic, so ttb is checkable by hand."""
        snap = dataclasses.replace(
            synthetic_snapshot(1, seed=0),
            alloc_cpu_milli=np.array([100_000], dtype=np.int64),
            alloc_mem_bytes=np.array([1 << 50], dtype=np.int64),
            alloc_pods=np.array([10_000], dtype=np.int64),
            used_cpu_req_milli=np.array([0], dtype=np.int64),
            used_mem_req_bytes=np.array([0], dtype=np.int64),
            pods_count=np.array([0], dtype=np.int64),
            healthy=np.array([True]),
        )
        spec = parse_stochastic_spec({
            "usage": {"cpu": {"dist": "point", "value": "1000m"},
                      "memory": {"dist": "point", "value": 1024}},
            "replicas": 100, "samples": 4, "seed": 1,
        })
        # capacity(h) = 100000 // round(1000 * (1 + 0.25 * h)); the
        # p-anything ladder is flat across samples (point usage).
        r = project_horizon(
            snap, spec, steps=8, step_s=900.0,
            growth_cpu_per_s=0.25 / 900.0, threshold=67,
        )
        ladder = r.quantiles[0.95].tolist()
        expect = [100_000 // round(1000 * (1 + 0.25 * h)) for h in range(8)]
        assert ladder == expect
        # First step with capacity < 67 is h=2 (100000//1500=66).
        assert r.time_to_breach_s[0.95] == pytest.approx(2 * 900.0)
        assert r.breached_within_horizon(0.95)
        assert r.min_capacity(0.95) == min(expect)

    def test_validation_and_cap(self, monkeypatch):
        snap = synthetic_snapshot(4, seed=2)
        spec = _spec(samples=4)
        for bad in (0, -1, True, 1.5):
            with pytest.raises(ValueError):
                project_horizon(snap, spec, steps=bad)
        with pytest.raises(ValueError):
            project_horizon(snap, spec, steps=2, step_s=0.0)
        monkeypatch.setenv("KCCAP_FORECAST_MAX_STEPS", "3")
        with pytest.raises(ValueError, match="KCCAP_FORECAST_MAX_STEPS"):
            project_horizon(snap, spec, steps=4)
        project_horizon(snap, spec, steps=3)  # at the cap: fine

    def test_wire_shape(self):
        snap = synthetic_snapshot(8, seed=1)
        r = project_horizon(snap, _spec(samples=8), steps=3,
                            growth_cpu_per_s=1e-4)
        w = r.to_wire()
        assert w["steps"] == 3 and w["horizon_s"] == 2 * 3600.0
        assert set(w["quantiles"]) == {"p50", "p90", "p95", "p99"}
        for label, ladder in w["quantiles"].items():
            assert len(ladder) == 3
            assert w["now"][label] == ladder[0]
        assert set(w["breached_within_horizon"]) <= set(w["quantiles"])


class TestPlanner:
    def test_catalog_grammar(self):
        shapes = parse_catalog(CATALOG)
        assert [s.name for s in shapes] == ["small", "big"]
        assert shapes[0].cpu_milli == 4000
        assert parse_catalog(CATALOG["shapes"]) == shapes  # bare list
        with pytest.raises(PlannerError, match="duplicate"):
            parse_catalog([CATALOG["shapes"][0]] * 2)
        with pytest.raises(PlannerError):
            parse_catalog([{**CATALOG["shapes"][0], "bogus": 1}])
        with pytest.raises(PlannerError):
            parse_catalog([{**CATALOG["shapes"][0], "unit_cost": 0}])
        with pytest.raises(PlannerError):
            parse_catalog([{**CATALOG["shapes"][0], "cpu": "4x"}])

    def test_certified_plan_restores_target(self):
        snap = synthetic_snapshot(20, seed=6)
        spec = _spec(replicas=300, samples=32, seed=11)
        catalog = parse_catalog(CATALOG)
        res = plan_capacity(snap, spec, catalog, target=300, quantile=0.9)
        assert res.certified and res.status == "certified"
        assert res.projected_quantile_capacity >= 300
        assert res.lp_bound <= res.total_cost + 1e-9
        assert res.satisfiable
        # Apply the purchase: the grown cluster needs nothing more.
        grown = apply_plan(snap, catalog, res.buy)
        assert grown.n_nodes == snap.n_nodes + sum(res.buy.values())
        again = plan_capacity(grown, spec, catalog, target=300, quantile=0.9)
        assert again.certified and sum(again.buy.values()) == 0
        assert again.base_quantile_capacity >= 300

    def test_unsatisfiable_is_never_certified(self):
        snap = synthetic_snapshot(4, seed=3)
        tiny = (CatalogShape(name="t", cpu_milli=1000,
                             mem_bytes=1 << 30, pods=4, unit_cost=1.0,
                             max_count=2),)
        res = plan_capacity(snap, _spec(replicas=10 ** 6), tiny,
                            target=10 ** 6)
        assert not res.satisfiable
        assert not res.certified
        assert res.status == "uncertified"
        assert res.uncertified_reason

    def test_drain_dual_is_verified(self):
        snap = synthetic_snapshot(30, seed=12)
        spec = _spec(replicas=50, samples=24, seed=5)
        res = plan_capacity(snap, spec, parse_catalog(CATALOG),
                            target=50, drain=True)
        d = res.drain
        assert d is not None and d["evaluated"]
        assert d["free_verified"] is True
        assert d["quantile_after_drain"] >= min(
            50, res.base_quantile_capacity
        )
        assert d["free_count"] + d["surplus_count"] <= snap.n_nodes

    def test_apply_plan_appends_fresh_nodes(self):
        snap = synthetic_snapshot(3, seed=1)
        catalog = parse_catalog(CATALOG)
        grown = apply_plan(snap, catalog, {"small": 2})
        assert grown.n_nodes == 5
        assert list(grown.names[-2:]) == ["small-plan-0", "small-plan-1"]
        assert grown.alloc_cpu_milli[-1] == 4000
        assert grown.pods_count[-1] == 0 and bool(grown.healthy[-1])
        with pytest.raises(PlannerError):
            apply_plan(snap, catalog, {"nope": 1})


class TestWatchGrammar:
    def _entry(self, **over):
        return {
            "name": "fc",
            "pod": {"cpuRequests": "500m", "memRequests": "1gb",
                    "replicas": "40"},
            "quantile": 0.95,
            "usage": {"cpu": USAGE["cpu"]},
            "samples": 16,
            "seed": 1,
            "min_replicas": 10,
            "horizon": {"steps": 4, "step_s": 600},
            **over,
        }

    def test_horizon_block_parses_with_defaults(self):
        spec = parse_watchlist({"watches": [self._entry()]})[0]
        assert spec.horizon_steps == 4 and spec.horizon_step_s == 600.0
        assert spec.to_wire()["horizon"] == {"steps": 4, "step_s": 600.0}
        wl = parse_watchlist({"watches": [self._entry(horizon={})]})
        assert wl[0].horizon_steps == 16  # DEFAULT_STEPS
        # Horizon relaxes the all-point-usage rejection: growth scaling
        # makes even a pure point spec vary across the projection.
        entry = self._entry(horizon={"steps": 2})
        del entry["usage"]
        wl = parse_watchlist({"watches": [entry]})
        assert wl[0].horizon_steps == 2

    @pytest.mark.parametrize(
        "mutation, fragment",
        [
            ({"horizon": {"steps": 0}}, "steps"),
            ({"horizon": {"steps": 4, "bogus": 1}}, "unknown horizon"),
            ({"horizon": {"step_s": -5}}, "step_s"),
            ({"horizon": "soon"}, "mapping"),
            ({"horizon": {"steps": 10 ** 9}}, "steps"),
        ],
    )
    def test_bad_horizon_blocks(self, mutation, fragment):
        with pytest.raises(WatchError, match=fragment):
            parse_watchlist({"watches": [self._entry(**mutation)]})

    def test_horizon_requires_quantile_and_excludes_gang(self):
        entry = self._entry()
        del entry["quantile"], entry["usage"], entry["samples"], entry["seed"]
        with pytest.raises(WatchError, match="quantile"):
            parse_watchlist({"watches": [entry]})
        bad = self._entry(gang={"ranks": 4})
        with pytest.raises(WatchError, match="mutually"):
            parse_watchlist({"watches": [bad]})
