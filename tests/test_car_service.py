"""Capacity-at-risk service wiring: the `car` op, quantile watches, and
the full alert funnel — WatchAlert → kccap_car_* gauges → /healthz 503
→ doctor FAILED → `kccap -car` exit 1 (the acceptance scenario)."""

import dataclasses
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.cli import main as cli_main
from kubernetesclustercapacity_tpu.masks import implicit_taint_mask
from kubernetesclustercapacity_tpu.service import (
    CapacityClient,
    CapacityServer,
)
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot
from kubernetesclustercapacity_tpu.stochastic import capacity_at_risk
from kubernetesclustercapacity_tpu.stochastic.distributions import (
    parse_stochastic_spec,
)
from kubernetesclustercapacity_tpu.telemetry.metrics import MetricsRegistry
from kubernetesclustercapacity_tpu.timeline import CapacityTimeline
from kubernetesclustercapacity_tpu.timeline.watchlist import parse_watchlist

USAGE = {
    "cpu": {"dist": "normal", "mean": "500m", "std": "200m"},
    "memory": {"dist": "lognormal", "mean": "1gb", "sigma": 0.4},
}

CAR_WATCHLIST = {
    "watches": [
        {
            "name": "web-p95",
            "pod": {
                "cpuRequests": "500m",
                "memRequests": "1gb",
                "replicas": "40",
            },
            "quantile": 0.95,
            "usage": {"cpu": USAGE["cpu"]},
            "samples": 32,
            "seed": 3,
            "min_replicas": 150,
        },
        {
            "name": "plain",
            "pod": {"cpuRequests": "2", "memRequests": "4gb"},
            "min_replicas": 1,
        },
    ]
}


def _starve(snap, factor=50):
    return dataclasses.replace(
        snap,
        alloc_cpu_milli=(
            np.asarray(snap.alloc_cpu_milli) // factor
        ).astype(np.int64),
    )


class TestCarOp:
    @pytest.fixture()
    def server(self):
        snap = synthetic_snapshot(40, seed=6)
        srv = CapacityServer(snap, port=0)
        srv.start()
        try:
            with CapacityClient(*srv.address) as client:
                yield srv, client, snap
        finally:
            srv.shutdown()

    def test_evaluate_matches_offline_engine(self, server):
        _, client, snap = server
        wire = client.car(usage=USAGE, replicas=40, samples=48, seed=11)
        offline = capacity_at_risk(
            snap,
            parse_stochastic_spec(
                {"usage": USAGE, "replicas": 40, "samples": 48, "seed": 11}
            ),
            mode=snap.semantics,
            node_mask=implicit_taint_mask(snap),
        )
        assert wire["quantiles"] == {
            k: int(v) for k, v in offline.to_wire()["quantiles"].items()
        }
        assert wire["prob_fit"] == offline.to_wire()["prob_fit"]
        assert wire["samples"] == 48 and wire["seed"] == 11
        # Seed-deterministic over the wire: a repeat call re-draws the
        # identical samples (the idempotent-retry contract).
        again = client.car(usage=USAGE, replicas=40, samples=48, seed=11)
        assert again["quantiles"] == wire["quantiles"]
        assert again["mean"] == wire["mean"]

    def test_custom_quantiles_and_binding(self, server):
        _, client, _ = server
        wire = client.car(
            usage=USAGE, replicas=10, samples=32, seed=1,
            quantiles=[0.5, 0.975],
        )
        assert set(wire["quantiles"]) == {"p50", "p97.5"}
        assert set(wire["binding"]) == {"p50", "p97.5"}
        # Attribution histograms count every node exactly once.
        n = 40
        for counts in wire["binding"].values():
            assert sum(counts.values()) == n

    def test_rendered_reports(self, server):
        _, client, _ = server
        out = client.car(usage=USAGE, samples=16, output="table")
        assert out["report"].startswith("capacity at risk")
        out = client.car(usage=USAGE, samples=16, output="json")
        assert json.loads(out["report"])["samples"] == 16

    @pytest.mark.parametrize(
        "params, fragment",
        [
            ({"usage": {"cpu": "1"}}, "both"),
            ({"usage": USAGE, "quantiles": []}, "non-empty"),
            ({"usage": USAGE, "quantiles": [1.5]}, "(0, 1)"),
            ({"usage": USAGE, "samples": 1}, "samples"),
        ],
    )
    def test_bad_requests_error_cleanly(self, server, params, fragment):
        _, client, _ = server
        with pytest.raises(RuntimeError) as ei:
            client.car(**params)
        assert fragment in str(ei.value)

    def test_status_form_disabled_without_quantile_watches(self, server):
        _, client, _ = server
        s = client.car()
        assert s == {"enabled": False, "watches": {}, "breached": []}


class TestCarFunnel:
    """The acceptance chain, end to end on one stack."""

    @pytest.fixture()
    def stack(self):
        reg = MetricsRegistry()
        tl = CapacityTimeline(
            parse_watchlist(CAR_WATCHLIST), depth=8, registry=reg
        )
        base = synthetic_snapshot(40, seed=6)
        srv = CapacityServer(base, port=0, timeline=tl, registry=reg)
        srv.start()
        try:
            with CapacityClient(*srv.address) as client:
                yield srv, client, base, reg, tl
        finally:
            srv.shutdown()
            tl.close()

    def test_breach_drives_every_surface(self, stack):
        from kubernetesclustercapacity_tpu.telemetry.exposition import (
            start_metrics_server,
        )
        from kubernetesclustercapacity_tpu.utils.doctor import doctor_report

        srv, client, base, reg, tl = stack

        # Healthy first: status ok, gauges populated, CLI exits 0.
        status = client.car()
        assert status["enabled"] is True
        assert status["breached"] == []
        w = status["watches"]["web-p95"]
        assert w["quantile"] == 0.95 and w["samples"] == 32
        assert w["last_total"] > 150
        s = reg.snapshot()
        assert (
            s["kccap_car_replicas"]["values"]['watch="web-p95"']
            == w["last_total"]
        )
        assert (
            s["kccap_car_alert_state"]["values"]['watch="web-p95"'] == 0
        )
        host, port = srv.address
        assert cli_main(["-car", f"{host}:{port}"]) == 0

        # Starve the cluster: P95 capacity dips under min_replicas.
        srv.replace_snapshot(_starve(base), warm=True)

        # 1. WatchAlert machine breached (and the plain watch's alert
        # state is irrelevant to the CaR slice).
        assert tl.alerts()["web-p95"]["state"] == "breached"
        assert tl.car_breached() == ["web-p95"]

        # 2. kccap_car_* gauges moved.
        s = reg.snapshot()
        assert (
            s["kccap_car_alert_state"]["values"]['watch="web-p95"'] == 2
        )
        assert (
            s["kccap_car_replicas"]["values"]['watch="web-p95"'] < 150
        )
        assert s["kccap_car_prob_fit"]["values"]['watch="web-p95"'] <= 1.0
        assert s["kccap_watch_breaches_total"]["values"][
            'watch="web-p95"'
        ] == 1

        # 3. /healthz 503 — the same healthy/status wiring server.main
        # installs (CaR breaches flip overall health; plain watch
        # breaches stay advisory).
        ms = start_metrics_server(
            reg,
            healthy=lambda: not tl.car_breached(),
            status=lambda: {"timeline": tl.stats()},
        )
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(ms.url + "/healthz")
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["ok"] is False
            assert body["timeline"]["car_breached"] == ["web-p95"]
        finally:
            ms.shutdown()

        # 4. doctor: hard FAILED line (exit-code relevant).
        checks = dict(
            doctor_report(
                backend_timeout_s=30.0,
                probe_code="print('DEVICES 0.0s cpu x1')",
                service_addr=srv.address,
            )
        )
        line = checks["capacity at risk"]
        assert line.startswith("FAILED")
        assert "web-p95" in line

        # 5. `kccap -car HOST:PORT` exit 1 while breached.
        assert cli_main(["-car", f"{host}:{port}"]) == 1

        # Recovery: restore capacity; state is recovered (sticky),
        # healthz healthy again, CLI back to 0.
        srv.replace_snapshot(base, warm=True)
        assert tl.alerts()["web-p95"]["state"] == "recovered"
        assert tl.car_breached() == []
        assert cli_main(["-car", f"{host}:{port}"]) == 0
        checks = dict(
            doctor_report(
                backend_timeout_s=30.0,
                probe_code="print('DEVICES 0.0s cpu x1')",
                service_addr=srv.address,
            )
        )
        assert checks["capacity at risk"].startswith("ok:")

    def test_watch_total_is_the_quantile_fit(self, stack):
        """A CaR watch capacity equals the fit of the quantile-realizing
        sample — the record stays node-granular and attributable."""
        _, client, base, _, tl = stack
        rec = tl.records()[-1]
        w = rec.watches["web-p95"]
        assert w.total == int(w.fits.sum())
        assert w.quantile == 0.95
        assert 0.0 <= w.prob_fit <= 1.0
        # And the wire carries the CaR fields.
        t = client.timeline()
        wt = t["records"][-1]["watches"]["web-p95"]
        assert wt["quantile"] == 0.95 and wt["samples"] == 32

    def test_timeline_stats_car_section_only_with_quantile_watches(self):
        tl = CapacityTimeline(
            parse_watchlist(
                {"watches": [{"name": "p", "pod": {"cpuRequests": "1"}}]}
            ),
            depth=4,
        )
        assert "car_breached" not in tl.stats()
        assert tl.car_breached() == [] and tl.car_status() == {}

    def test_telemetry_off_keeps_observe_registry_silent(self, monkeypatch):
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        reg = MetricsRegistry()
        tl = CapacityTimeline(
            parse_watchlist(CAR_WATCHLIST), depth=4, registry=reg
        )
        tl.observe(synthetic_snapshot(12, seed=2), 1)
        assert reg.snapshot() == {}


class TestCarCLI:
    def _spec_file(self, tmp_path, **overrides):
        spec = {
            "usage": USAGE,
            "replicas": 40,
            "samples": 32,
            "seed": 5,
            "confidence": 0.9,
            **overrides,
        }
        p = tmp_path / "spec.json"
        p.write_text(json.dumps(spec))
        return str(p)

    def _snapshot_file(self, tmp_path, n=40):
        snap = synthetic_snapshot(n, seed=6)
        path = tmp_path / "snap.npz"
        snap.save(str(path))
        return str(path), snap

    def test_car_spec_offline_table_and_exit_codes(
        self, tmp_path, capsys
    ):
        snap_path, snap = self._snapshot_file(tmp_path)
        spec_path = self._spec_file(tmp_path)
        rc = cli_main(["-snapshot", snap_path, "-car-spec", spec_path])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.startswith("capacity at risk")
        assert "p95" in out and "verdict: SCHEDULABLE" in out
        # An unsatisfiable spec exits 1 by its own confidence bar.
        rc = cli_main([
            "-snapshot", snap_path,
            "-car-spec", self._spec_file(tmp_path, replicas=10 ** 9),
        ])
        assert rc == 1
        assert "NOT SCHEDULABLE" in capsys.readouterr().out

    def test_car_spec_json_matches_library(self, tmp_path, capsys):
        snap_path, snap = self._snapshot_file(tmp_path)
        spec_path = self._spec_file(tmp_path)
        rc = cli_main([
            "-snapshot", snap_path, "-car-spec", spec_path,
            "-output", "json",
        ])
        assert rc == 0
        got = json.loads(capsys.readouterr().out)
        want = capacity_at_risk(
            snap,
            parse_stochastic_spec(json.loads(open(spec_path).read())),
            node_mask=implicit_taint_mask(snap),
        ).to_wire()
        assert got["quantiles"] == want["quantiles"]
        assert got["prob_fit"] == want["prob_fit"]

    def test_car_spec_overrides_and_errors(self, tmp_path, capsys):
        snap_path, snap = self._snapshot_file(tmp_path)
        spec_path = self._spec_file(tmp_path)
        rc = cli_main([
            "-snapshot", snap_path, "-car-spec", spec_path,
            "-car-samples", "16", "-car-seed", "77", "-output", "json",
        ])
        assert rc == 0
        got = json.loads(capsys.readouterr().out)
        assert got["samples"] == 16 and got["seed"] == 77
        # Bad spec file: clean ERROR, exit 1, no traceback.
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"usage": {"cpu": "1"}}))
        rc = cli_main(["-snapshot", snap_path, "-car-spec", str(bad)])
        assert rc == 1
        assert "ERROR" in capsys.readouterr().out
        rc = cli_main([
            "-snapshot", snap_path, "-car-spec", spec_path,
            "-car-samples", "1",
        ])
        assert rc == 1
        # Non-TPU backends are fit-only cross-checks.
        rc = cli_main([
            "-snapshot", snap_path, "-car-spec", spec_path,
            "-backend", "cpu",
        ])
        assert rc == 1

    def test_car_status_cli_not_configured_and_bad_addr(self, capsys):
        assert cli_main(["-car", "nonsense"]) == 1
        snap = synthetic_snapshot(8, seed=0)
        srv = CapacityServer(snap, port=0)
        srv.start()
        try:
            host, port = srv.address
            rc = cli_main(["-car", f"{host}:{port}"])
            out = capsys.readouterr().out
            assert rc == 1  # no quantile watches = scriptable failure
            assert "no quantile watches" in out
            rc = cli_main(["-car", f"{host}:{port}", "-output", "json"])
            assert rc == 1
            assert json.loads(capsys.readouterr().out)["enabled"] is False
        finally:
            srv.shutdown()
