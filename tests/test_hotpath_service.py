"""Hot-path service behavior: cache invalidation under snapshot churn,
micro-batching over the wire, and the throughput smoke test.

The load-bearing guarantee: ``replace_snapshot``/``reload``/``update``
under concurrent sweeps never serves a stale generation — every response
must equal the totals of the generation the flight recorder says
answered it (the dump op's per-record snapshot-generation field is the
witness).
"""

import threading

import numpy as np
import pytest

from kubernetesclustercapacity_tpu import devcache
from kubernetesclustercapacity_tpu.ops.fit import sweep_snapshot
from kubernetesclustercapacity_tpu.scenario import random_scenario_grid
from kubernetesclustercapacity_tpu.service import CapacityClient, CapacityServer
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot

GRID_N, GRID_SEED = 6, 77


def _expected_totals(snap):
    grid = random_scenario_grid(GRID_N, seed=GRID_SEED)
    totals, _ = sweep_snapshot(snap, grid)
    return totals.tolist()


class TestGenerationConsistency:
    def test_replace_under_concurrent_sweeps_never_tears(self):
        """Hammer sweeps from 8 threads while the snapshot flips A→B→A…;
        every response's totals must equal the totals of the generation
        its flight record carries — a torn read (new snapshot, old mask,
        or half-swapped state) would produce totals matching neither."""
        snap_a = synthetic_snapshot(64, seed=1)
        snap_b = synthetic_snapshot(64, seed=2, mean_utilization=0.7)
        expected = {1: _expected_totals(snap_a)}
        assert expected[1] != _expected_totals(snap_b)  # distinguishable

        srv = CapacityServer(
            snap_a, port=0, flight_records=4096, batch_window_ms=0.5
        )
        srv.start()
        try:
            responses: dict[str, list] = {}
            resp_lock = threading.Lock()
            stop = threading.Event()

            def sweeper():
                with CapacityClient(*srv.address, trace=True) as c:
                    while not stop.is_set():
                        r = c.sweep(random={"n": GRID_N, "seed": GRID_SEED})
                        with resp_lock:
                            responses[c.last_trace_id] = r["totals"]

            threads = [
                threading.Thread(target=sweeper) for _ in range(8)
            ]
            for t in threads:
                t.start()
            for i in range(6):
                new = snap_b if i % 2 == 0 else snap_a
                srv.replace_snapshot(new)
                expected[srv.generation] = _expected_totals(new)
            stop.set()
            for t in threads:
                t.join(30)

            with CapacityClient(*srv.address) as c:
                dump = c.dump()
            gen_by_trace = {
                r["trace_id"]: r["generation"]
                for r in dump["records"]
                if r["op"] == "sweep" and r["trace_id"]
            }
            assert responses  # the hammer actually ran
            checked = 0
            for trace_id, totals in responses.items():
                gen = gen_by_trace.get(trace_id)
                if gen is None:
                    continue  # fell off the (generous) ring
                assert totals == expected[gen], (
                    f"trace {trace_id}: totals do not match the "
                    f"generation ({gen}) that answered"
                )
                checked += 1
            assert checked >= len(responses) // 2
        finally:
            srv.shutdown()

    def test_replace_invalidates_devcache_entries(self):
        snap_a = synthetic_snapshot(32, seed=3)
        snap_b = synthetic_snapshot(32, seed=4)
        srv = CapacityServer(snap_a, port=0)
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                c.sweep(random={"n": 4, "seed": 1}, kernel="exact")
                entries_before = devcache.CACHE.stats()["entries"]
                srv.replace_snapshot(snap_b)
                c.sweep(random={"n": 4, "seed": 1}, kernel="exact")
            # A's entries were dropped on swap; B's took their place —
            # the cache never grows per reload.
            assert devcache.CACHE.stats()["entries"] <= entries_before + 1
        finally:
            srv.shutdown()

    def test_warm_prestages_new_generation(self):
        snap_a = synthetic_snapshot(48, seed=5)
        snap_b = synthetic_snapshot(48, seed=6)
        srv = CapacityServer(snap_a, port=0)
        srv.start()
        try:
            st0 = devcache.CACHE.stats()
            srv.replace_snapshot(snap_b, warm=True)
            st1 = devcache.CACHE.stats()
            # The publish itself staged B (misses moved), so the first
            # reader hits a warm cache.
            assert st1["misses"] > st0["misses"]
            with CapacityClient(*srv.address) as c:
                before_hits = devcache.CACHE.stats()["hits"]
                c.sweep(random={"n": 4, "seed": 2}, kernel="exact")
                assert devcache.CACHE.stats()["hits"] > before_hits
        finally:
            srv.shutdown()


class TestServerBatching:
    def test_info_hot_path_opt_in(self):
        snap = synthetic_snapshot(16, seed=7)
        srv = CapacityServer(snap, port=0, batch_window_ms=1.0)
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                assert "hot_path" not in c.info()  # default shape pinned
                hp = c.info(hot_path=True)["hot_path"]
            assert set(hp) == {
                "devcache", "node_bucket_floor", "batching", "grouping",
            }
            assert hp["batching"]["window_ms"] == 1.0
            assert hp["batching"]["max_batch"] == 32
            # 16 nodes is under the grouping floor: reported, not engaged
            assert hp["grouping"]["enabled"] is True
            assert hp["grouping"]["engaged"] is False
            assert hp["grouping"]["group_min_count"] >= 1
        finally:
            srv.shutdown()

    def test_batching_disabled_reports_none(self):
        snap = synthetic_snapshot(16, seed=7)
        srv = CapacityServer(snap, port=0, batch_window_ms=0)
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                r = c.sweep(random={"n": 4, "seed": 1})
                hp = c.info(hot_path=True)["hot_path"]
            assert hp["batching"] is None
            assert len(r["totals"]) == 4
        finally:
            srv.shutdown()

    def test_concurrent_sweeps_batch_and_match_solo(self):
        """N concurrent client sweeps against a live batching server:
        the batch-size histogram must move, and every response must be
        bit-identical to its solo (batching-off) answer."""
        snap = synthetic_snapshot(128, seed=8)
        srv = CapacityServer(
            snap, port=0, batch_window_ms=25.0, batch_max=16,
            max_inflight=16,
        )
        srv.start()
        try:
            seeds = list(range(10))
            solo = {
                s: sweep_snapshot(
                    snap, random_scenario_grid(5, seed=s)
                )[0].tolist()
                for s in seeds
            }
            results: dict[int, list] = {}
            barrier = threading.Barrier(len(seeds))

            def worker(seed):
                with CapacityClient(*srv.address) as c:
                    barrier.wait()
                    results[seed] = c.sweep(
                        random={"n": 5, "seed": seed}
                    )["totals"]

            threads = [
                threading.Thread(target=worker, args=(s,)) for s in seeds
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            for s in seeds:
                assert results[s] == solo[s]
            st = srv._batcher.stats
            assert st["dispatches"] >= 1
            assert st["batched_requests"] > 0  # at least one real batch
            assert st["dispatches"] < len(seeds)  # it actually coalesced
        finally:
            srv.shutdown()

    def test_expired_deadline_sheds_alone_inside_burst(self):
        """A shed request in a concurrent burst sheds by itself: the
        other requests answer normally (acceptance: 'a shed request
        inside a batch sheds alone')."""
        from kubernetesclustercapacity_tpu.resilience import Deadline

        snap = synthetic_snapshot(64, seed=9)
        srv = CapacityServer(snap, port=0, batch_window_ms=20.0)
        srv.start()
        try:
            outcomes: dict[int, object] = {}
            barrier = threading.Barrier(4)

            def worker(i):
                with CapacityClient(*srv.address) as c:
                    barrier.wait()
                    try:
                        if i == 0:
                            # Pre-expired absolute deadline on the wire.
                            outcomes[i] = c.call(
                                "sweep", random={"n": 3, "seed": 1},
                                deadline=Deadline.after(-1.0).to_wire(),
                            )
                        else:
                            outcomes[i] = c.sweep(
                                random={"n": 3, "seed": 1}
                            )
                    except Exception as e:  # noqa: BLE001
                        outcomes[i] = e
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert isinstance(outcomes[0], Exception)
            assert "deadline" in str(outcomes[0]).lower()
            for i in (1, 2, 3):
                assert isinstance(outcomes[i], dict)
                assert len(outcomes[i]["totals"]) == 3
        finally:
            srv.shutdown()


@pytest.mark.slow
class TestThroughputSmoke:
    def test_concurrent_sweep_throughput_zero_diffs(self):
        """The CI throughput smoke: 64 sweeps from 16 concurrent clients
        against a live batching server — batch-size histogram count > 0
        and zero correctness diffs against the solo path."""
        snap = synthetic_snapshot(1000, seed=10)
        srv = CapacityServer(
            snap, port=0, batch_window_ms=5.0, batch_max=32,
            max_inflight=32,
        )
        srv.start()
        try:
            per_client = 4
            n_clients = 16
            diffs: list = []
            solo_cache: dict = {}
            solo_lock = threading.Lock()

            def solo(seed):
                with solo_lock:
                    if seed not in solo_cache:
                        solo_cache[seed] = sweep_snapshot(
                            snap, random_scenario_grid(8, seed=seed)
                        )[0].tolist()
                    return solo_cache[seed]

            def worker(base):
                with CapacityClient(*srv.address) as c:
                    for k in range(per_client):
                        seed = (base * per_client + k) % 10
                        got = c.sweep(random={"n": 8, "seed": seed})
                        if got["totals"] != solo(seed):
                            diffs.append((seed, got["totals"]))

            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(n_clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not diffs
            size_hist = srv._batcher._m_size.labels()
            assert size_hist.count > 0  # histogram moved
            assert srv._batcher.stats["batched_requests"] > 0
        finally:
            srv.shutdown()
