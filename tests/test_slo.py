"""SLO burn-rate engine: spec grammar, the pure window math pinned
against a numpy oracle, the registry counter source, monitor alert
transitions + gauges + JSONL, the KCCAP_TELEMETRY=0 pin, and the
end-to-end acceptance scenario — a fault-proxy-stalled service burns
its availability budget and transitions ok→breached→recovered through
gauges, /healthz, doctor, and the kccap -slo-status exit code."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import kubernetesclustercapacity_tpu as kcc
from kubernetesclustercapacity_tpu.service.server import CapacityServer
from kubernetesclustercapacity_tpu.telemetry.metrics import MetricsRegistry
from kubernetesclustercapacity_tpu.telemetry.slo import (
    SLOError,
    SLOMonitor,
    burn_rate,
    estimate_quantile,
    load_slos,
    parse_slos,
    registry_source,
)


def _spec(**over):
    entry = {"name": "avail", "availability": 0.9}
    entry.update(over)
    return parse_slos([entry])[0]


class TestGrammar:
    def test_latency_objective_parses(self):
        s = parse_slos(
            {"slos": [{"name": "lat", "op": "sweep",
                       "latency": "p99 < 80ms"}]}
        )[0]
        assert s.kind == "latency" and s.op == "sweep"
        assert s.quantile == pytest.approx(0.99)
        assert s.threshold_s == pytest.approx(0.08)
        assert s.budget == pytest.approx(0.01)
        assert s.objective == "p99 < 80ms"

    def test_latency_seconds_unit_and_fractional_quantile(self):
        s = parse_slos([{"name": "l", "latency": "p99.9 < 2s"}])[0]
        assert s.threshold_s == pytest.approx(2.0)
        assert s.budget == pytest.approx(0.001)

    def test_availability_percent_and_fraction(self):
        assert _spec(availability="99.9%").target == pytest.approx(0.999)
        assert _spec(availability=0.95).target == pytest.approx(0.95)

    def test_window_overrides_and_defaults(self):
        s = _spec(short_window_s=5, long_window_s=50, fast_burn=3)
        assert (s.short_window_s, s.long_window_s, s.fast_burn) == (
            5.0, 50.0, 3.0,
        )
        d = _spec()
        assert d.short_window_s == 60.0 and d.long_window_s == 600.0
        assert d.fast_burn == 14.0

    @pytest.mark.parametrize(
        "entry,needle",
        [
            ({"availability": 0.9}, "'name'"),
            ({"name": "x"}, "exactly one"),
            ({"name": "x", "latency": "p99 < 80ms",
              "availability": 0.9}, "exactly one"),
            ({"name": "x", "latency": "99 < 80ms"}, "cannot parse"),
            ({"name": "x", "latency": "p99 < -80ms"}, "cannot parse"),
            ({"name": "x", "latency": "p0 < 80ms"}, "quantile"),
            ({"name": "x", "availability": 1.5}, "between 0 and 1"),
            ({"name": "x", "availability": "nope%"}, "bad availability"),
            ({"name": "x", "availability": 0.9, "bogus": 1}, "unknown"),
            ({"name": "x", "availability": 0.9,
              "short_window_s": -1}, "positive"),
            ({"name": "x", "availability": 0.9, "short_window_s": 600,
              "long_window_s": 60}, "short_window_s must be <"),
            ({"name": "x", "availability": 0.9, "op": ""}, "'op'"),
        ],
    )
    def test_bad_entries_rejected(self, entry, needle):
        with pytest.raises(SLOError, match=None) as ei:
            parse_slos([entry])
        assert needle in str(ei.value)

    def test_duplicate_names_rejected(self):
        with pytest.raises(SLOError, match="duplicate"):
            parse_slos([
                {"name": "x", "availability": 0.9},
                {"name": "x", "latency": "p99 < 80ms"},
            ])

    def test_empty_and_unknown_top_level_rejected(self):
        with pytest.raises(SLOError):
            parse_slos({"slos": []})
        with pytest.raises(SLOError, match="unknown top-level"):
            parse_slos({"slos": [{"name": "x", "availability": 0.9}],
                        "extra": 1})

    def test_load_slos_json_file(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps(
            {"slos": [{"name": "a", "availability": "99%"}]}
        ))
        specs = load_slos(str(p))
        assert [s.name for s in specs] == ["a"]


def _oracle_burn(ts, tot, bad, *, now, window_s, budget):
    """Independent numpy implementation of the burn-rate definition:
    head = newest sample at/before now; baseline = newest sample
    at/before the window start, else the oldest in-history sample."""
    ts = np.asarray(ts, dtype=float)
    tot = np.asarray(tot, dtype=float)
    bad = np.asarray(bad, dtype=float)
    in_hist = np.flatnonzero(ts <= now)
    if in_hist.size == 0:
        return None
    head = in_hist.max()
    at_or_before_start = np.flatnonzero(ts <= now - window_s)
    base = (
        at_or_before_start.max()
        if at_or_before_start.size
        else in_hist.min()
    )
    if head == base:
        return None
    d_total = tot[head] - tot[base]
    d_bad = bad[head] - bad[base]
    if d_total <= 0:
        return 0.0
    return (d_bad / d_total) / budget


class TestBurnRateOracle:
    def test_simple_window(self):
        samples = [(0, 0, 0), (30, 100, 50), (60, 200, 100)]
        assert burn_rate(
            samples, now=60, window_s=60, budget=0.01
        ) == pytest.approx(50.0)

    def test_partial_history_uses_oldest(self):
        samples = [(100, 10, 0), (110, 20, 5)]
        # window start 110-600 < first ts: partial-window fallback.
        assert burn_rate(
            samples, now=110, window_s=600, budget=0.1
        ) == pytest.approx((5 / 10) / 0.1)

    def test_no_traffic_is_zero_not_none(self):
        samples = [(0, 10, 1), (30, 10, 1)]
        assert burn_rate(samples, now=30, window_s=60, budget=0.1) == 0.0

    def test_single_sample_is_none(self):
        assert burn_rate([(0, 1, 0)], now=10, window_s=5,
                         budget=0.1) is None
        assert burn_rate([], now=10, window_s=5, budget=0.1) is None

    def test_future_samples_ignored(self):
        samples = [(0, 0, 0), (10, 100, 0), (999, 10**6, 10**6)]
        assert burn_rate(
            samples, now=10, window_s=20, budget=0.5
        ) == 0.0

    def test_bad_budget_rejected(self):
        with pytest.raises(SLOError):
            burn_rate([(0, 0, 0)], now=1, window_s=1, budget=0.0)

    def test_property_random_series_match_numpy_oracle(self):
        # 200 random synthetic cumulative counter series × random
        # windows: the pure-python window math must agree with the
        # independent numpy implementation exactly.
        rng = np.random.default_rng(4242)
        for trial in range(200):
            n = int(rng.integers(1, 40))
            ts = np.sort(rng.uniform(0, 1000, size=n))
            d_tot = rng.integers(0, 50, size=n)
            frac = rng.uniform(0, 1, size=n)
            d_bad = np.floor(d_tot * frac).astype(int)
            tot = np.cumsum(d_tot)
            bad = np.cumsum(d_bad)
            samples = list(zip(ts.tolist(), tot.tolist(), bad.tolist()))
            now = float(rng.uniform(-50, 1100))
            window_s = float(rng.uniform(1, 800))
            budget = float(rng.uniform(0.001, 0.5))
            got = burn_rate(
                samples, now=now, window_s=window_s, budget=budget
            )
            want = _oracle_burn(
                ts, tot, bad, now=now, window_s=window_s, budget=budget
            )
            if want is None:
                assert got is None, (trial, got)
            else:
                assert got == pytest.approx(want), (trial, got, want)


class TestEstimateQuantile:
    def test_interpolates_inside_the_bucket(self):
        buckets = {"0.1": 50, "0.2": 100, "+Inf": 100}
        assert estimate_quantile(buckets, 100, 0.5) == pytest.approx(0.1)
        assert estimate_quantile(buckets, 100, 0.75) == pytest.approx(
            0.15
        )

    def test_empty_histogram_is_none(self):
        assert estimate_quantile({}, 0, 0.5) is None

    def test_inf_tail_clamps_to_last_finite(self):
        buckets = {"0.1": 0, "+Inf": 10}
        assert estimate_quantile(buckets, 10, 0.5) == pytest.approx(0.1)


class TestRegistrySource:
    def test_latency_violations_from_buckets(self):
        reg = MetricsRegistry()
        read = registry_source(reg)
        lat = reg.histogram(
            "kccap_request_latency_seconds",
            "End-to-end dispatch latency, by op.",
            ("op",),
        )
        for v in (0.01, 0.05, 0.2, 0.3, 0.05):
            lat.observe(v, op="sweep")
        lat.observe(5.0, op="fit")
        spec = parse_slos(
            [{"name": "l", "op": "sweep", "latency": "p90 < 100ms"}]
        )[0]
        total, bad = read(spec)
        assert (total, bad) == (5, 2)  # 0.2 and 0.3 are above 0.1
        all_ops = parse_slos([{"name": "l2", "latency": "p90 < 100ms"}])[0]
        total, bad = read(all_ops)
        assert (total, bad) == (6, 3)

    def test_availability_counts_errors_and_sheds(self):
        reg = MetricsRegistry()
        read = registry_source(reg)
        req = reg.counter("kccap_requests_total", "", ("op",))
        err = reg.counter(
            "kccap_request_errors_total", "", ("op", "error")
        )
        shed = reg.counter("kccap_deadline_shed_total", "")
        req.inc(10, op="sweep")
        req.inc(5, op="fit")
        err.inc(2, op="sweep", error="ValueError")
        err.inc(1, op="fit", error="RuntimeError")
        shed.inc(3)
        spec = _spec()
        assert read(spec) == (15, 6)
        sweep_only = _spec(name="s", op="sweep")
        assert read(sweep_only) == (10, 5)  # 2 errors + 3 sheds


def _mono_series(values):
    """An injected source yielding successive (total, bad) samples."""
    it = iter(values)
    last = {"v": (0, 0)}

    def read(_spec):
        try:
            last["v"] = next(it)
        except StopIteration:
            pass
        return last["v"]

    return read


class TestMonitor:
    def test_transitions_ok_breached_recovered(self, tmp_path):
        spec = _spec(short_window_s=10, long_window_s=100, fast_burn=2)
        clock = {"t": 0.0}
        # totals advance 100/step; bad: none, then a storm, then clean.
        series = [
            (100, 0), (200, 0),
            (300, 80), (400, 160),
            (500, 160), (600, 160), (700, 160),
        ]
        log = tmp_path / "slo.jsonl"
        mon = SLOMonitor(
            [spec], source=_mono_series(series),
            registry=MetricsRegistry(), log=str(log),
            time_fn=lambda: clock["t"],
        )
        states = []
        for _ in series:
            out = mon.evaluate()
            states.append(out["avail"]["state"])
            clock["t"] += 5.0
        # budget 0.1, storm bad fraction 0.8 → burn 8 > 2 on both
        # windows → breached; clean traffic drains the short window →
        # recovered (distinguishable from ok on purpose).
        assert states[0] == "ok" and states[1] == "ok"
        assert "breached" in states
        assert states[-1] == "recovered"
        assert mon.fast_burning is False
        st = mon.status()["avail"]
        assert st["breaches"] == 1 and st["recoveries"] == 1
        mon.close()
        lines = [json.loads(ln) for ln in log.read_text().splitlines()]
        assert [ln["transition"] for ln in lines] == [
            "breached", "recovered",
        ]
        assert all(ln["kind"] == "slo_alert" for ln in lines)

    def test_gauges_and_breach_counter(self):
        spec = _spec(short_window_s=10, long_window_s=100, fast_burn=2)
        reg = MetricsRegistry()
        clock = {"t": 0.0}
        mon = SLOMonitor(
            [spec], source=_mono_series([(100, 0), (200, 100)]),
            registry=reg, time_fn=lambda: clock["t"],
        )
        mon.evaluate()
        clock["t"] = 5.0
        mon.evaluate()
        s = reg.snapshot()
        assert s["kccap_slo_alert_state"]["values"]['slo="avail"'] == 2
        assert (
            s["kccap_slo_burn_rate"]["values"]['slo="avail",window="short"']
            == pytest.approx(10.0)
        )
        assert s["kccap_slo_breaches_total"]["values"]['slo="avail"'] == 1
        assert mon.fast_burning
        assert mon.wire()["fast_burning"] is True
        assert mon.stats()["breached"] == ["avail"]
        mon.close()

    def test_burn_on_only_one_window_does_not_breach(self):
        # Long window healthy (a deep good-traffic history), short
        # window spiking: no page — the multi-window AND is the
        # false-positive filter.
        spec = _spec(short_window_s=10, long_window_s=1000, fast_burn=2)
        clock = {"t": 0.0}
        series = [(10_000, 0), (10_200, 0), (10_400, 0), (10_500, 90)]
        mon = SLOMonitor(
            [spec], source=_mono_series(series),
            registry=MetricsRegistry(), time_fn=lambda: clock["t"],
        )
        out = None
        for _ in series:
            out = mon.evaluate()
            clock["t"] += 5.0
        # At the last eval: short-window baseline is the t=5 sample
        # (300 requests, 90 bad → burn 3); the long window reaches back
        # to t=0 (500 requests, 90 bad → burn 1.8 < 2): state holds ok.
        assert out["avail"]["short_burn"] > 2
        assert out["avail"]["long_burn"] < 2
        assert out["avail"]["state"] == "ok"
        mon.close()

    def test_disabled_telemetry_makes_zero_registry_calls(
        self, monkeypatch
    ):
        monkeypatch.setenv("KCCAP_TELEMETRY", "0")
        reg = MetricsRegistry()
        mon = SLOMonitor(
            [_spec(short_window_s=1, long_window_s=10, fast_burn=1)],
            source=_mono_series([(10, 0), (20, 10)]),
            registry=reg,
        )
        mon.evaluate()
        mon.evaluate()
        assert reg.snapshot() == {}  # not even family registration
        mon.close()

    def test_monitor_needs_specs_and_a_source(self):
        with pytest.raises(SLOError):
            SLOMonitor([], registry=MetricsRegistry())
        with pytest.raises(SLOError):
            SLOMonitor([_spec()])


def _mib(n):
    return n * 1024 * 1024


class TestEndToEnd:
    """The acceptance scenario: a latency/availability objective
    violated by a stalled (fault-proxy) service transitions
    ok→breached→recovered through gauges, /healthz, doctor, and the
    kccap -slo-status exit code."""

    @pytest.fixture()
    def stack(self, tmp_path):
        from kubernetesclustercapacity_tpu.telemetry.exposition import (
            start_metrics_server,
        )

        snap = kcc.synthetic_snapshot(24, seed=31)
        reg = MetricsRegistry()
        specs = parse_slos([
            {
                "name": "availability",
                "availability": 0.9,
                "short_window_s": 0.4,
                "long_window_s": 30,
                "fast_burn": 1.5,
            }
        ])
        mon = SLOMonitor(specs, registry=reg)
        srv = CapacityServer(snap, port=0, registry=reg, slo=mon)
        srv.start()

        # The same /healthz wiring server.main() builds.
        def _status():
            mon.evaluate()
            return {"slo": mon.stats()}

        metrics = start_metrics_server(
            reg, port=0,
            healthy=lambda: not mon.fast_burning,
            status=_status,
        )
        try:
            yield srv, mon, reg, metrics
        finally:
            metrics.shutdown()
            mon.close()
            srv.shutdown()

    def _healthz(self, metrics):
        url = f"http://{metrics.address[0]}:{metrics.address[1]}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_breach_and_recovery_on_every_surface(self, stack, capsys):
        from kubernetesclustercapacity_tpu.cli import main as cli_main
        from kubernetesclustercapacity_tpu.resilience import Deadline
        from kubernetesclustercapacity_tpu.service.client import (
            CapacityClient,
        )
        from kubernetesclustercapacity_tpu.testing_faults import (
            FaultPlan,
            FaultProxy,
        )
        from kubernetesclustercapacity_tpu.utils.doctor import doctor_report

        srv, mon, reg, metrics = stack
        host, port = srv.address
        addr = f"{host}:{port}"

        # --- phase 1: healthy traffic → ok everywhere.
        with CapacityClient(host, port) as c:
            for _ in range(8):
                c.ping()
        mon.evaluate()
        time.sleep(0.05)
        mon.evaluate()
        assert not mon.fast_burning
        code, body = self._healthz(metrics)
        assert code == 200 and body["slo"]["breached"] == []
        assert cli_main(["-slo-status", addr]) == 0
        out = capsys.readouterr().out
        assert "verdict: ok" in out

        # --- phase 2: the stalled path — a fault proxy stalls every
        # frame past the caller's deadline, so the server sheds each
        # request on arrival (kccap_deadline_shed_total) — the
        # availability objective's budget burns.
        n_bad = 6
        proxy = FaultProxy(
            srv.address, FaultPlan(["stall"] * n_bad), stall_s=0.25
        )
        proxy.start()
        try:
            from kubernetesclustercapacity_tpu.resilience import (
                RetryPolicy,
            )

            with CapacityClient(
                *proxy.address,
                retry=RetryPolicy(max_attempts=1, base_delay_s=0.01),
                deadline_s=0.1,
                timeout_s=2.0,
            ) as c:
                for _ in range(n_bad):
                    with pytest.raises(Exception):
                        c.ping()
                    time.sleep(0.02)
        finally:
            time.sleep(0.4)  # let the stalled frames reach the server
            proxy.stop()
        mon.evaluate()
        time.sleep(0.05)
        mon.evaluate()
        assert mon.fast_burning, mon.status()
        s = reg.snapshot()
        assert (
            s["kccap_slo_alert_state"]["values"]['slo="availability"'] == 2
        )
        code, body = self._healthz(metrics)
        assert code == 503
        assert body["slo"]["breached"] == ["availability"]
        assert cli_main(["-slo-status", addr]) == 1
        out = capsys.readouterr().out
        assert "FAST BURN" in out and "breached" in out
        # Doctor: the "latency & SLO" line is a hard FAILED.
        checks = doctor_report(
            backend_timeout_s=10.0,
            probe_code="print('DEVICES 0s D x1')",
            service_addr=(host, port),
        )
        by_name = dict(checks)
        assert "latency & SLO" in by_name
        assert by_name["latency & SLO"].startswith("FAILED"), by_name
        assert "fast-burning" in by_name["latency & SLO"]

        # --- phase 3: recovery — clean traffic, the short window
        # drains, the machine recovers (NOT ok: "it dipped" is the
        # point of the state), /healthz flips back, exit code clears.
        deadline_clear = time.time() + 10
        with CapacityClient(host, port) as c:
            while time.time() < deadline_clear:
                for _ in range(4):
                    c.ping()
                mon.evaluate()
                if not mon.fast_burning:
                    break
                time.sleep(0.1)
        assert not mon.fast_burning, mon.status()
        st = mon.status()["availability"]
        assert st["state"] == "recovered" and st["breaches"] == 1
        code, body = self._healthz(metrics)
        assert code == 200
        assert cli_main(["-slo-status", addr]) == 0
        out = capsys.readouterr().out
        assert "recovered" in out
        checks = doctor_report(
            backend_timeout_s=10.0,
            probe_code="print('DEVICES 0s D x1')",
            service_addr=(host, port),
        )
        line = dict(checks)["latency & SLO"]
        assert line.startswith("ok:") and "availability=recovered" in line
        assert "latency p50=" in line

    def test_slo_op_disabled_shape(self):
        snap = kcc.synthetic_snapshot(8, seed=32)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        try:
            assert srv.dispatch({"op": "slo"}) == {"enabled": False}
        finally:
            srv.shutdown()

    def test_cli_slo_status_against_unconfigured_server(self, capsys):
        from kubernetesclustercapacity_tpu.cli import main as cli_main

        snap = kcc.synthetic_snapshot(8, seed=33)
        srv = CapacityServer(snap, port=0, registry=MetricsRegistry())
        srv.start()
        try:
            host, port = srv.address
            assert cli_main(["-slo-status", f"{host}:{port}"]) == 1
            assert "not enabled" in capsys.readouterr().out
        finally:
            srv.shutdown()

    def test_server_main_rejects_bad_slo_file(self, tmp_path):
        from kubernetesclustercapacity_tpu.service.server import (
            main as server_main,
        )

        import os
        import shutil

        fixture = tmp_path / "f.json"
        snap_path = tmp_path / "slo.json"
        snap_path.write_text(json.dumps({"slos": [{"name": "x"}]}))
        shutil.copy(
            os.path.join(
                os.path.dirname(__file__), "fixtures", "kind-3node.json"
            ),
            fixture,
        )
        rc = server_main(
            ["-snapshot", str(fixture), "-slo", str(snap_path),
             "-port", "0"]
        )
        assert rc == 1
