"""Native columnar pod-walk parity: C extension vs pure-Python packers.

The native walk (`native/ingest.cc`) must be invisible: identical arrays
on well-formed fixtures, identical exceptions on malformed ones (it
reports non-JSON-shaped input and the packers rerun the pure loop).
"""

import json
import os

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.fixtures import synthetic_fixture
from kubernetesclustercapacity_tpu.native import ingest
from kubernetesclustercapacity_tpu.snapshot import snapshot_from_fixture

pytestmark = pytest.mark.skipif(
    not ingest.available(), reason="no C++ toolchain for the native walk"
)

_FIELDS = (
    "alloc_cpu_milli", "alloc_mem_bytes", "alloc_pods",
    "used_cpu_req_milli", "used_cpu_lim_milli",
    "used_mem_req_bytes", "used_mem_lim_bytes",
    "pods_count", "healthy",
)


def _pack_both(fixture, **kw):
    """Pack with the native walk and with it disabled; returns the pair."""
    native = snapshot_from_fixture(fixture, **kw)
    os.environ["KCC_DISABLE_NATIVE_INGEST"] = "1"
    try:
        pure = snapshot_from_fixture(fixture, **kw)
    finally:
        del os.environ["KCC_DISABLE_NATIVE_INGEST"]
    return native, pure


def _assert_equal(fixture, **kw):
    native, pure = _pack_both(fixture, **kw)
    for f in _FIELDS:
        np.testing.assert_array_equal(
            getattr(native, f), getattr(pure, f), err_msg=f
        )
    assert set(native.extended) == set(pure.extended)
    for r in native.extended:
        np.testing.assert_array_equal(native.extended[r][0], pure.extended[r][0])
        np.testing.assert_array_equal(native.extended[r][1], pure.extended[r][1])


class TestParity:
    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize("semantics", ["reference", "strict"])
    def test_randomized(self, seed, semantics):
        fx = synthetic_fixture(
            40, seed=seed, unhealthy_frac=0.2, unparseable_mem_frac=0.1,
            unscheduled_running_pods=3, taint_frac=0.1,
        )
        # De-intern so the native walk sees production-unique objects.
        _assert_equal(json.loads(json.dumps(fx)), semantics=semantics)

    def test_extended_resources(self):
        fx = synthetic_fixture(20, seed=7)
        fx["nodes"][0]["allocatable"]["nvidia.com/gpu"] = "8"
        pod = fx["pods"][0]
        fx["pods"][0] = dict(
            pod,
            containers=[
                {"resources": {"requests": {"cpu": "1", "nvidia.com/gpu": "2"}}}
            ],
        )
        _assert_equal(
            fx, semantics="strict",
            extended_resources=("nvidia.com/gpu", "ephemeral-storage"),
        )

    def test_explicit_null_and_missing_fields(self):
        node = {
            "name": "n0",
            "allocatable": {"cpu": "4", "memory": "8388608Ki", "pods": "10"},
            "conditions": [{"type": "c", "status": "False"}] * 4,
        }
        fx = {
            "nodes": [node],
            "pods": [
                # missing resources entirely
                {"name": "a", "namespace": "d", "nodeName": "n0",
                 "phase": "Running", "containers": [{}]},
                # empty resources / requests-only / limits-only
                {"name": "b", "namespace": "d", "nodeName": "n0",
                 "phase": "Running",
                 "containers": [
                     {"resources": {}},
                     {"resources": {"requests": {"cpu": "100m"}}},
                     {"resources": {"limits": {"memory": "64Mi"}}},
                 ]},
                # explicit null memory; missing phase (survives selector)
                {"name": "c", "namespace": "d", "nodeName": "n0",
                 "containers": [
                     {"resources": {"requests": {"memory": None}}}
                 ]},
                # no containers key at all
                {"name": "d", "namespace": "d", "nodeName": "n0",
                 "phase": "Running"},
            ],
        }
        _assert_equal(fx, semantics="reference")
        _assert_equal(fx, semantics="strict")

    def test_phantom_grouping_and_duplicate_names(self):
        """Orphan pods group under the phantom '' name; duplicate node
        names share one usage group — both must survive the native walk."""
        node = lambda nm, unhealthy: {  # noqa: E731
            "name": nm,
            "allocatable": {"cpu": "4", "memory": "8388608Ki", "pods": "10"},
            "conditions": (
                [{"type": "c", "status": "True"}]
                + [{"type": "c", "status": "False"}] * 3
                if unhealthy
                else [{"type": "c", "status": "False"}] * 4
            ),
        }
        mk_pod = lambda nm, node_name: {  # noqa: E731
            "name": nm, "namespace": "d", "nodeName": node_name,
            "phase": "Running",
            "containers": [{"resources": {"requests": {"cpu": "250m"}}}],
        }
        fx = {
            "nodes": [node("dup", False), node("x", True), node("dup", False)],
            "pods": [
                mk_pod("p1", "dup"), mk_pod("p2", ""), mk_pod("p3", "dup"),
            ],
        }
        _assert_equal(fx, semantics="reference")


class TestFallback:
    def test_non_list_containers_matches_pure_error(self):
        node = {
            "name": "n0",
            "allocatable": {"cpu": "4", "memory": "8388608Ki", "pods": "10"},
            "conditions": [{"type": "c", "status": "False"}] * 4,
        }
        # containers as tuple: native reports None, pure loop handles it
        # (tuples iterate fine) — outputs must still be equal.
        fx = {
            "nodes": [node],
            "pods": [{"name": "a", "namespace": "d", "nodeName": "n0",
                      "phase": "Running",
                      "containers": ({"resources":
                                      {"requests": {"cpu": "1"}}},)}],
        }
        assert ingest.walk_reference(fx["pods"], frozenset()) is None
        _assert_equal(fx, semantics="reference")
        _assert_equal(fx, semantics="strict")

    def test_null_resources_raises_identically(self):
        node = {
            "name": "n0",
            "allocatable": {"cpu": "4", "memory": "8388608Ki", "pods": "10"},
            "conditions": [{"type": "c", "status": "False"}] * 4,
        }
        fx = {
            "nodes": [node],
            "pods": [{"name": "a", "namespace": "d", "nodeName": "n0",
                      "phase": "Running",
                      "containers": [{"resources": None}]}],
        }
        with pytest.raises(AttributeError):
            snapshot_from_fixture(fx, semantics="reference")
        os.environ["KCC_DISABLE_NATIVE_INGEST"] = "1"
        try:
            with pytest.raises(AttributeError):
                snapshot_from_fixture(fx, semantics="reference")
        finally:
            del os.environ["KCC_DISABLE_NATIVE_INGEST"]

    def test_non_string_node_name_skips_like_pure(self):
        node = {
            "name": "n0",
            "allocatable": {"cpu": "4", "memory": "8388608Ki", "pods": "10"},
            "conditions": [{"type": "c", "status": "False"}] * 4,
        }
        fx = {
            "nodes": [node],
            "pods": [{"name": "a", "namespace": "d", "nodeName": 123,
                      "phase": "Running",
                      "containers": [{"resources":
                                      {"requests": {"cpu": "1"}}}]}],
        }
        _assert_equal(fx, semantics="strict")

    def test_unhashable_phase_raises_both_ways(self):
        node = {
            "name": "n0",
            "allocatable": {"cpu": "4", "memory": "8388608Ki", "pods": "10"},
            "conditions": [{"type": "c", "status": "False"}] * 4,
        }
        fx = {
            "nodes": [node],
            "pods": [{"name": "a", "namespace": "d", "nodeName": "n0",
                      "phase": ["not-hashable"], "containers": []}],
        }
        for disable in (False, True):
            if disable:
                os.environ["KCC_DISABLE_NATIVE_INGEST"] = "1"
            try:
                with pytest.raises(TypeError):
                    snapshot_from_fixture(fx, semantics="reference")
            finally:
                if disable:
                    del os.environ["KCC_DISABLE_NATIVE_INGEST"]
