"""Explainability: vectorized attribution vs a brute-force re-derivation.

The oracle here is deliberately independent: a per-node pure-Python loop
that re-implements the mode semantics (uint64 CPU views, Go wrap/trunc
memory math, the Q1 conditional pod-cap overwrite, strict clamping) and
the documented attribution rule (first minimum in cpu ≺ memory ≺ pods
order; health/mask overrides).  Marginal answers are checked against
reality, not against a formula: the reported increment must actually
buy +1 when the full evaluator re-runs the node, one less must not, and
no other node may offer a cheaper verified increment.
"""

import json
import os

import numpy as np
import pytest

import kubernetesclustercapacity_tpu as kcc
from kubernetesclustercapacity_tpu.explain import (
    BINDING_CPU,
    BINDING_MASKED,
    BINDING_MEMORY,
    BINDING_NAMES,
    BINDING_PODS,
    BINDING_UNHEALTHY,
    explain_snapshot,
)
from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.ops.fit import fit_per_node

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "kind-3node.json"
)

_U64 = 1 << 64


def _i64(v: int) -> int:
    v %= _U64
    return v - _U64 if v >= 1 << 63 else v


def _go_trunc_div(a: int, b: int) -> int:
    """Go int64 division: truncate toward zero (sane-divisor domain)."""
    q = abs(a) // abs(b)
    return q if (a < 0) == (b < 0) else -q


def brute_force_explain(snap, cpu_req, mem_req, mode, node_mask=None):
    """Independent per-node re-derivation of (fit, binding code)."""
    cr = int(cpu_req) % _U64
    mr = int(mem_req)
    fits, codes = [], []
    for i in range(snap.n_nodes):
        ac = int(snap.alloc_cpu_milli[i]) % _U64
        uc = int(snap.used_cpu_req_milli[i]) % _U64
        cpu_fit = 0 if ac <= uc else _i64((ac - uc) // cr)
        am = int(snap.alloc_mem_bytes[i])
        um = int(snap.used_mem_req_bytes[i])
        mem_fit = (
            0 if am <= um else _i64(_go_trunc_div(_i64(am - um), mr))
        )
        ap = int(snap.alloc_pods[i])
        pc = int(snap.pods_count[i])
        healthy = bool(snap.healthy[i])
        pre = min(cpu_fit, mem_fit)
        if mode == "reference":
            if pre >= ap:
                fit, code = ap - pc, BINDING_PODS
            else:
                fit = pre
                code = BINDING_CPU if cpu_fit <= mem_fit else BINDING_MEMORY
        else:
            slots = max(ap - pc, 0)
            fit = max(min(pre, slots), 0)
            if not healthy:
                fit = 0
            if cpu_fit <= mem_fit and cpu_fit <= slots:
                code = BINDING_CPU
            elif mem_fit <= slots:
                code = BINDING_MEMORY
            else:
                code = BINDING_PODS
        if not healthy:
            code = BINDING_UNHEALTHY
        if node_mask is not None and not bool(node_mask[i]):
            fit, code = 0, BINDING_MASKED
        fits.append(fit)
        codes.append(code)
    return np.asarray(fits, dtype=np.int64), np.asarray(codes)


def random_snapshot(n, seed, *, q1_heavy=False):
    """A synthetic snapshot mutated to hit every attribution branch:
    unhealthy nodes, saturated (used > alloc) rows, and tiny/negative
    pod headroom so the Q1 overwrite fires (including its negative
    ``alloc_pods - pods_count`` replacement)."""
    rng = np.random.default_rng(seed)
    snap = kcc.synthetic_snapshot(n, seed=seed)
    unhealthy = rng.random(n) < 0.1
    snap.healthy[unhealthy] = False
    sat = rng.random(n) < 0.15  # memory-saturated rows
    snap.used_mem_req_bytes[sat] = snap.alloc_mem_bytes[sat] + rng.integers(
        0, 1 << 20, size=int(sat.sum())
    )
    if q1_heavy:
        # Small pod caps vs pod counts: min(cpu_fit, mem_fit) >= alloc_pods
        # fires the Q1 overwrite, sometimes with a NEGATIVE replacement.
        few = rng.random(n) < 0.5
        snap.alloc_pods[few] = rng.integers(0, 4, size=int(few.sum()))
        snap.pods_count[few] = rng.integers(0, 6, size=int(few.sum()))
    return snap


class TestAttributionProperty:
    @pytest.mark.parametrize("mode", ["reference", "strict"])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_brute_force_1k_nodes(self, mode, seed):
        snap = random_snapshot(1000, seed, q1_heavy=(seed % 2 == 0))
        grid = kcc.random_scenario_grid(4, seed=seed + 100)
        result = explain_snapshot(snap, grid, mode=mode)
        assert result.size == 4
        for s in range(grid.size):
            bf_fits, bf_codes = brute_force_explain(
                snap,
                int(grid.cpu_request_milli[s]),
                int(grid.mem_request_bytes[s]),
                mode,
            )
            np.testing.assert_array_equal(result.fits[s], bf_fits)
            np.testing.assert_array_equal(result.binding[s], bf_codes)

    @pytest.mark.parametrize("mode", ["reference", "strict"])
    def test_fits_bit_identical_to_fit_kernel(self, mode):
        snap = random_snapshot(257, 7, q1_heavy=True)
        grid = kcc.random_scenario_grid(8, seed=9)
        result = explain_snapshot(snap, grid, mode=mode)
        for s in range(grid.size):
            kernel = np.asarray(
                fit_per_node(
                    snap.alloc_cpu_milli,
                    snap.alloc_mem_bytes,
                    snap.alloc_pods,
                    snap.used_cpu_req_milli,
                    snap.used_mem_req_bytes,
                    snap.pods_count,
                    snap.healthy,
                    int(grid.cpu_request_milli[s]),
                    int(grid.mem_request_bytes[s]),
                    mode=mode,
                )
            )
            np.testing.assert_array_equal(result.fits[s], kernel)

    def test_q1_overwrite_attributed_to_pods(self):
        # One node where cpu/mem allow 10 but only 2 pod slots exist:
        # reference overwrites (fit = 2 - 5 = -3!), strict clamps to 0.
        snap = kcc.ClusterSnapshot(
            names=["n0"],
            alloc_cpu_milli=[10_000],
            alloc_mem_bytes=[10 << 30],
            alloc_pods=[2],
            used_cpu_req_milli=[0],
            used_cpu_lim_milli=[0],
            used_mem_req_bytes=[0],
            used_mem_lim_bytes=[0],
            pods_count=[5],
            healthy=[True],
        )
        grid = kcc.ScenarioGrid(
            cpu_request_milli=[1000], mem_request_bytes=[1 << 30],
            replicas=[1],
        )
        ref = explain_snapshot(snap, grid, mode="reference")
        assert int(ref.fits[0][0]) == -3
        assert int(ref.binding[0][0]) == BINDING_PODS
        strict = explain_snapshot(snap, grid, mode="strict")
        assert int(strict.fits[0][0]) == 0
        assert int(strict.binding[0][0]) == BINDING_PODS

    def test_unhealthy_and_masked_codes(self):
        snap = random_snapshot(64, 3)
        mask = np.ones(64, dtype=bool)
        mask[:5] = False
        grid = kcc.random_scenario_grid(2, seed=5)
        result = explain_snapshot(
            snap, grid, mode="strict", node_mask=mask
        )
        names = result.binding_names(0)
        for i in range(64):
            if not mask[i]:
                assert names[i] == "masked"
                assert result.fits[0][i] == 0
            elif not snap.healthy[i]:
                assert names[i] == "unhealthy"
        counts = result.binding_counts(0)
        assert counts["masked"] == 5
        assert sum(counts.values()) == 64
        assert set(counts) == set(BINDING_NAMES)


def _apply_delta(snap, i, resource, delta):
    """(alloc_cpu, alloc_mem, alloc_pods) for node i with +delta on R."""
    ac = int(snap.alloc_cpu_milli[i])
    am = int(snap.alloc_mem_bytes[i])
    ap = int(snap.alloc_pods[i])
    if resource == "cpu":
        ac += delta
    elif resource == "memory":
        am += delta
    else:
        ap += delta
    return ac, am, ap


def _node_fit(snap, i, s, grid, mode, resource=None, delta=0):
    ac, am, ap = _apply_delta(snap, i, resource, delta) if resource else (
        int(snap.alloc_cpu_milli[i]),
        int(snap.alloc_mem_bytes[i]),
        int(snap.alloc_pods[i]),
    )
    return fit_arrays_python(
        [ac], [am], [ap],
        [int(snap.used_cpu_req_milli[i])],
        [int(snap.used_mem_req_bytes[i])],
        [int(snap.pods_count[i])],
        int(grid.cpu_request_milli[s]),
        int(grid.mem_request_bytes[s]),
        mode=mode,
        healthy=[bool(snap.healthy[i])],
    )[0]


class TestMarginal:
    @pytest.mark.parametrize("mode", ["reference", "strict"])
    @pytest.mark.parametrize("seed", [0, 1])
    def test_marginal_verified_minimal_and_globally_best(self, mode, seed):
        snap = random_snapshot(200, seed, q1_heavy=True)
        grid = kcc.random_scenario_grid(2, seed=seed + 50)
        result = explain_snapshot(snap, grid, mode=mode)
        for s in range(grid.size):
            marginal = result.marginal(s, verify_limit=None)
            assert set(marginal) == {"cpu", "memory", "pods"}
            for resource, m in marginal.items():
                if m is None:
                    continue
                i = m["node_index"]
                before = int(result.fits[s][i])
                # The reported delta delivers +1 under FULL semantics...
                after = _node_fit(
                    snap, i, s, grid, mode, resource, m["delta"]
                )
                assert after > before, (mode, s, resource, m)
                # ...and is minimal on that node at integer resolution.
                if m["delta"] > 1:
                    almost = _node_fit(
                        snap, i, s, grid, mode, resource, m["delta"] - 1
                    )
                    assert almost <= before, (mode, s, resource, m)
            # Brute-force oracle: no node's verified minimal increment
            # beats the reported one (scan ALL nodes independently).
            for resource, m in marginal.items():
                best = self._brute_best(snap, s, grid, mode, result, resource)
                if m is None:
                    assert best is None, (mode, s, resource, best)
                else:
                    assert best is not None
                    assert best[0] == m["delta"], (mode, s, resource)

    @staticmethod
    def _brute_best(snap, s, grid, mode, result, resource):
        """Independent minimal verified increment for resource R."""
        best = None
        cr = int(grid.cpu_request_milli[s]) % _U64
        mr = int(grid.mem_request_bytes[s])
        for i in range(snap.n_nodes):
            if not snap.healthy[i]:
                continue
            before = int(result.fits[s][i])
            target = before + 1
            if resource == "cpu":
                head = (int(snap.alloc_cpu_milli[i]) % _U64) - (
                    int(snap.used_cpu_req_milli[i]) % _U64
                )
                delta = target * cr - head
            elif resource == "memory":
                head = int(snap.alloc_mem_bytes[i]) - int(
                    snap.used_mem_req_bytes[i]
                )
                delta = target * mr - head
            else:
                if mode == "strict":
                    delta = target - max(
                        int(snap.alloc_pods[i]) - int(snap.pods_count[i]), 0
                    )
                else:
                    delta = 1
            if delta <= 0 or delta > 1 << 62:
                continue
            if best is not None and delta >= best[0]:
                continue  # cannot improve; skip the expensive re-eval
            if _node_fit(snap, i, s, grid, mode, resource, delta) > before:
                best = (delta, i)
        return best

    def test_reference_q1_pods_marginal_is_one_slot(self):
        # cpu/mem allow 10, cap is 3 with 1 pod running: fit = 3-1 = 2;
        # +1 allocatable pod slot (and nothing else) buys the next one.
        snap = kcc.ClusterSnapshot(
            names=["n0"],
            alloc_cpu_milli=[10_000],
            alloc_mem_bytes=[10 << 30],
            alloc_pods=[3],
            used_cpu_req_milli=[0],
            used_cpu_lim_milli=[0],
            used_mem_req_bytes=[0],
            used_mem_lim_bytes=[0],
            pods_count=[1],
            healthy=[True],
        )
        grid = kcc.ScenarioGrid(
            cpu_request_milli=[1000], mem_request_bytes=[1 << 30],
            replicas=[1],
        )
        result = explain_snapshot(snap, grid, mode="reference")
        assert int(result.fits[0][0]) == 2
        m = result.marginal(0)
        assert m["pods"] == {
            "delta": 1, "node": "n0", "node_index": 0, "unit": "slots",
        }
        # cpu/memory already clear the cap: no increment there buys +1.
        assert m["cpu"] is None and m["memory"] is None


class TestExplainSurfaces:
    def test_headroom_and_saturation_shapes(self):
        snap = random_snapshot(64, 11)
        grid = kcc.random_scenario_grid(2, seed=3)
        result = explain_snapshot(snap, grid, mode="strict")
        head = result.headroom(0)
        assert set(head) == {"cpu_milli", "mem_bytes", "pod_slots"}
        for arr in head.values():
            assert arr.shape == (64,)
            assert (arr >= 0).all()
        sat = result.saturation(1)
        assert sat["nodes"] == 64
        assert set(sat["binding_counts"]) == set(BINDING_NAMES)
        assert 0 <= sat["cpu_utilization"]["p50"] <= sat["cpu_utilization"]["max"]
        # Saturated rows exist by construction (used_mem > alloc_mem).
        assert sat["mem_utilization"]["saturated_nodes"] >= 1

    def test_report_renderers(self):
        from kubernetesclustercapacity_tpu.fixtures import load_fixture
        from kubernetesclustercapacity_tpu.report import (
            explain_json_report,
            explain_table_report,
        )

        snap = kcc.snapshot_from_fixture(load_fixture(FIXTURE))
        scenario = kcc.scenario_from_flags(
            cpuRequests="200m", memRequests="250mb", replicas="10"
        )
        grid = kcc.ScenarioGrid.from_scenarios([scenario])
        result = explain_snapshot(snap, grid)
        table = explain_table_report(result)
        assert "BINDING" in table and "marginal (+1 replica):" in table
        assert "total possible replicas: 109" in table
        doc = json.loads(explain_json_report(result))
        assert doc["total_possible_replicas"] == 109
        assert len(doc["nodes"]) == snap.n_nodes
        assert doc["binding_counts"]["cpu"] >= 1
        assert set(doc["marginal"]) == {"cpu", "memory", "pods"}

    def test_service_explain_op(self):
        from kubernetesclustercapacity_tpu.fixtures import load_fixture
        from kubernetesclustercapacity_tpu.service import (
            CapacityClient,
            CapacityServer,
        )

        fixture = load_fixture(FIXTURE)
        snap = kcc.snapshot_from_fixture(fixture)
        server = CapacityServer(snap, port=0, fixture=fixture)
        server.start()
        try:
            with CapacityClient(*server.address) as client:
                out = client.explain(
                    cpuRequests="200m", memRequests="250mb", replicas="10"
                )
                fit = client.fit(
                    cpuRequests="200m", memRequests="250mb", replicas="10"
                )
                # explain explains the numbers fit actually returns.
                assert out["total"] == fit["total"]
                assert out["schedulable"] == fit["schedulable"]
                assert len(out["binding"]) == snap.n_nodes
                assert set(out["binding_counts"]) == set(BINDING_NAMES)
                assert set(out["marginal"]) == {"cpu", "memory", "pods"}
                assert "report" not in out
                rendered = client.explain(
                    cpuRequests="200m", memRequests="250mb",
                    replicas="10", output="table",
                )
                assert "BINDING" in rendered["report"]
        finally:
            server.shutdown()

    def test_cli_explain_flag(self, capsys):
        from kubernetesclustercapacity_tpu.cli import main

        rc = main(
            [
                "-snapshot", FIXTURE, "-cpuRequests=200m",
                "-memRequests=250mb", "-replicas=10", "-explain",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "BINDING" in out and "marginal (+1 replica):" in out
        rc = main(
            [
                "-snapshot", FIXTURE, "-cpuRequests=200m",
                "-memRequests=250mb", "-replicas=10", "-explain",
                "-output", "json",
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        assert doc["total_possible_replicas"] == 109

    def test_cli_explain_rejects_cpu_backend(self, capsys):
        from kubernetesclustercapacity_tpu.cli import main

        rc = main(["-snapshot", FIXTURE, "-explain", "-backend", "cpu"])
        assert rc == 1
        assert "-backend tpu" in capsys.readouterr().out
