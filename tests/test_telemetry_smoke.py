"""End-to-end telemetry smoke: a served snapshot + a metrics port.

The acceptance path in one test class: start a CapacityServer with an
exposition endpoint over its registry, drive real ops through
CapacityClient over TCP, scrape ``/metrics`` over HTTP, and assert the
per-op counters and latency histograms moved; a client-sent trace ID
must land in the server's JSONL trace log.  Also pins the bench-side
registry dump (``KCC_BENCH_METRICS_OUT``).
"""

import json
import os
import pathlib
import sys
import urllib.request

import pytest

from test_telemetry import FIXTURE, parse_exposition

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


@pytest.fixture()
def stack(tmp_path):
    """(server, client, metrics_url, trace_path) — the full wiring the
    ``kccap-server -metrics-port ... -trace-log ...`` flags produce,
    assembled in-process on a private registry."""
    from kubernetesclustercapacity_tpu.fixtures import load_fixture
    from kubernetesclustercapacity_tpu.service import (
        CapacityClient,
        CapacityServer,
    )
    from kubernetesclustercapacity_tpu.snapshot import snapshot_from_fixture
    from kubernetesclustercapacity_tpu.telemetry.exposition import (
        start_metrics_server,
    )
    from kubernetesclustercapacity_tpu.telemetry.metrics import (
        MetricsRegistry,
    )

    fixture = load_fixture(FIXTURE)
    snap = snapshot_from_fixture(fixture, semantics="reference")
    registry = MetricsRegistry()
    trace_path = str(tmp_path / "trace.jsonl")
    server = CapacityServer(
        snap, port=0, fixture=fixture, registry=registry,
        trace_log=trace_path,
    )
    server.start()
    metrics = start_metrics_server(registry)
    client = CapacityClient(*server.address, registry=registry)
    yield server, client, metrics.url, trace_path
    client.close()
    metrics.shutdown()
    server.shutdown()


def scrape(url: str) -> dict:
    return parse_exposition(
        urllib.request.urlopen(url + "/metrics").read().decode()
    )


class TestSmoke:
    def test_counters_and_histograms_move_under_load(self, stack):
        server, client, url, _ = stack
        before = scrape(url)
        assert before.get('kccap_requests_total{op="fit"}', 0) == 0

        client.ping()
        for _ in range(3):
            client.fit(cpuRequests="200m", memRequests="250mb",
                       replicas="10")
        sweep = client.sweep(random={"n": 8, "seed": 1}, kernel="exact")
        assert sweep["scenarios"] == 8

        after = scrape(url)
        assert after['kccap_requests_total{op="ping"}'] == 1
        assert after['kccap_requests_total{op="fit"}'] == 3
        assert after['kccap_requests_total{op="sweep"}'] == 1
        # Latency histograms moved with the counters, and stayed
        # internally consistent (cumulative, +Inf == count).
        assert after['kccap_request_latency_seconds_count{op="fit"}'] == 3
        assert (
            after['kccap_request_latency_seconds_bucket{op="fit",le="+Inf"}']
            == 3
        )
        assert after['kccap_request_latency_seconds_sum{op="fit"}'] > 0
        # The client shares the registry: its transport counters are in
        # the same scrape.
        assert after["kccap_client_calls_total"] == 5
        # Nothing in flight once the calls returned.
        assert after["kccap_requests_in_flight"] == 0

    def test_error_and_shed_counters_move(self, stack):
        server, client, url, _ = stack
        with pytest.raises(RuntimeError):
            client.call("bogus_op")
        with pytest.raises(RuntimeError):  # server-side DeadlineExpired
            client.call("fit", deadline=1.0)  # epoch-second 1: long gone
        after = scrape(url)
        assert (
            after['kccap_request_errors_total{op="unknown",error="ValueError"}']
            == 1
        )
        assert after["kccap_deadline_shed_total"] == 1

    def test_trace_id_round_trips_into_trace_log(self, stack):
        from kubernetesclustercapacity_tpu.telemetry.tracing import (
            new_trace_id,
        )

        server, client, url, trace_path = stack
        tid = new_trace_id()
        client.fit(cpuRequests="200m", memRequests="250mb", trace_id=tid)
        client.ping()  # un-traced: logged with empty trace_id
        records = [
            json.loads(ln)
            for ln in open(trace_path, encoding="utf-8")
        ]
        fit_recs = [r for r in records if r["op"] == "fit"]
        assert [r["trace_id"] for r in fit_recs] == [tid]
        assert fit_recs[0]["status"] == "ok"
        assert fit_recs[0]["duration_ms"] >= 0

    def test_healthz_ok(self, stack):
        _, _, url, _ = stack
        assert json.loads(
            urllib.request.urlopen(url + "/healthz").read()
        ) == {"ok": True}

    def test_scrape_is_valid_prometheus_text(self, stack):
        server, client, url, _ = stack
        client.ping()
        text = urllib.request.urlopen(url + "/metrics").read().decode()
        seen_types: dict = {}
        for line in text.splitlines():
            if line.startswith("# TYPE"):
                _, _, name, mtype = line.split(" ", 3)
                assert mtype in ("counter", "gauge", "histogram")
                assert name not in seen_types  # one TYPE per family
                seen_types[name] = mtype
            elif line and not line.startswith("#"):
                name_labels, _, value = line.rpartition(" ")
                float(value.replace("+Inf", "inf"))  # every value parses
        assert "kccap_requests_total" in seen_types


class TestBenchMetricsDump:
    def test_dump_writes_registry_snapshot(self, tmp_path, monkeypatch):
        out = tmp_path / "metrics.json"
        monkeypatch.setenv("KCC_BENCH_METRICS_OUT", str(out))
        sys.modules.pop("bench", None)
        sys.path.insert(0, _REPO_ROOT)
        try:
            import bench

            # Put something real in the default registry first (the
            # same one the bench child's sweeps feed via sweep_auto).
            from kubernetesclustercapacity_tpu.telemetry.metrics import (
                REGISTRY,
            )

            REGISTRY.counter("bench_dump_probe_total").inc()
            bench._maybe_dump_metrics()
        finally:
            sys.path.pop(0)
            sys.modules.pop("bench", None)
        snap = json.loads(out.read_text())
        assert snap["bench_dump_probe_total"]["values"][""] == 1

    def test_no_env_no_file(self, tmp_path, monkeypatch):
        monkeypatch.delenv("KCC_BENCH_METRICS_OUT", raising=False)
        sys.modules.pop("bench", None)
        sys.path.insert(0, _REPO_ROOT)
        try:
            import bench

            bench._maybe_dump_metrics()  # must be a silent no-op
        finally:
            sys.path.pop(0)
            sys.modules.pop("bench", None)
        assert list(tmp_path.iterdir()) == []
