"""ReplicaSet client + chaos suite for the replicated serving plane.

The acceptance bar (ISSUE 10): under replica kill, plane-stream stall,
and garbled-link faults, every client-observed answer is bit-identical
to the sequential oracle at its STAMPED generation (both semantics
modes), and the generation watermark never regresses within a client
session — asserted on every response, across the whole suite.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.oracle import fit_arrays_python
from kubernetesclustercapacity_tpu.resilience import (
    CircuitBreaker,
    OverloadedError,
    RetryPolicy,
)
from kubernetesclustercapacity_tpu.service.client import CapacityClient
from kubernetesclustercapacity_tpu.service.plane import (
    AdmissionController,
    PlanePublisher,
    PlaneSubscriber,
)
from kubernetesclustercapacity_tpu.service.replicaset import (
    ReplicaSet,
    ReplicaSetError,
    StaleReadError,
    parse_endpoints,
)
from kubernetesclustercapacity_tpu.service.server import CapacityServer
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot
from kubernetesclustercapacity_tpu.testing_faults import FaultPlan, FaultProxy


def _wait_for(predicate, timeout_s=10.0, interval_s=0.01, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def _base_snapshot(semantics, n=24, seed=0):
    snap = synthetic_snapshot(n, seed=seed)
    healthy = snap.healthy.copy()
    if semantics == "strict":
        healthy[::5] = False  # exercise the health mask in strict mode
    return dataclasses.replace(snap, semantics=semantics, healthy=healthy)


def _next_generation(snap, seed):
    """Deterministic churn: usage moves, one node's pod count moves, and
    (in strict mode) one health flip — all diff-visible fields."""
    rng = np.random.default_rng(seed)
    used_cpu = snap.used_cpu_req_milli + rng.integers(
        0, 300, size=snap.n_nodes, dtype=np.int64
    )
    used_mem = snap.used_mem_req_bytes + (
        rng.integers(0, 64, size=snap.n_nodes, dtype=np.int64) * 1024
    )
    pods = snap.pods_count.copy()
    pods[int(rng.integers(0, snap.n_nodes))] += 1
    healthy = snap.healthy.copy()
    if snap.semantics == "strict":
        flip = int(rng.integers(0, snap.n_nodes))
        healthy[flip] = ~healthy[flip]
    return dataclasses.replace(
        snap,
        used_cpu_req_milli=used_cpu,
        used_mem_req_bytes=used_mem,
        pods_count=pods,
        healthy=healthy,
    )


def _oracle_totals(snap, cpu, mem, replicas):
    """The sequential python oracle: totals/schedulable per scenario,
    exactly as the fit kernels must answer."""
    totals, sched = [], []
    for c, m, r in zip(cpu, mem, replicas):
        fits = fit_arrays_python(
            snap.alloc_cpu_milli,
            snap.alloc_mem_bytes,
            snap.alloc_pods,
            snap.used_cpu_req_milli,
            snap.used_mem_req_bytes,
            snap.pods_count,
            int(c),
            int(m),
            mode=snap.semantics,
            healthy=snap.healthy,
        )
        total = int(sum(fits))
        totals.append(total)
        sched.append(total >= int(r))
    return totals, sched


# ---------------------------------------------------------------------------
# Unit behavior
# ---------------------------------------------------------------------------
class TestParseEndpoints:
    def test_grammar(self):
        assert parse_endpoints("a:1,b:2") == [("a", 1), ("b", 2)]
        assert parse_endpoints([("h", 9), "x:3"]) == [("h", 9), ("x", 3)]
        with pytest.raises(ValueError):
            parse_endpoints("")
        with pytest.raises(ValueError):
            parse_endpoints("nocolon")


class TestFailover:
    def test_failover_past_dead_endpoint(self):
        snap = _base_snapshot("reference")
        srv = CapacityServer(snap, port=0)
        srv.start()
        try:
            rs = ReplicaSet(
                [("127.0.0.1", 1), srv.address],  # first endpoint: dead port
                connect_timeout_s=0.5,
            )
            try:
                assert rs.ping() == "pong"
                # Sticky preference moved to the live endpoint.
                assert rs.ping() == "pong"
                assert rs.stats()["endpoints"][0]["breaker"] in (
                    "open", "half_open", "closed",
                )
            finally:
                rs.close()
        finally:
            srv.shutdown()

    def test_all_dead_raises_replicaset_error(self):
        rs = ReplicaSet(
            [("127.0.0.1", 1), ("127.0.0.1", 2)],
            connect_timeout_s=0.2, rounds=1,
        )
        try:
            with pytest.raises(ReplicaSetError):
                rs.ping()
        finally:
            rs.close()

    def test_overloaded_fails_over_to_sibling(self):
        """An admission-shed (rps bucket empty) is retryable-elsewhere:
        the call lands on the sibling, not on the caller's lap."""
        snap = _base_snapshot("reference")
        capped = CapacityServer(
            snap, port=0,
            admission=AdmissionController(rps=0.0001, burst=1.0),
        )
        open_srv = CapacityServer(snap, port=0)
        capped.start()
        open_srv.start()
        try:
            rs = ReplicaSet([capped.address, open_srv.address])
            try:
                # Drain the capped endpoint's single burst token.
                ok1 = rs.sweep(
                    cpu_request_milli=[100], mem_request_bytes=[10 ** 8],
                    replicas=[1],
                )
                ok2 = rs.sweep(
                    cpu_request_milli=[100], mem_request_bytes=[10 ** 8],
                    replicas=[1],
                )
                assert ok1["totals"] == ok2["totals"]
                failovers = rs.registry.counter(
                    "kccap_replicaset_failovers_total", "", ("cause",)
                )
                assert failovers.labels(cause="overloaded").value >= 1
            finally:
                rs.close()
        finally:
            capped.shutdown()
            open_srv.shutdown()

    def test_single_endpoint_surfaces_overloaded(self):
        """A single-endpoint client has no 'elsewhere': the typed
        refusal surfaces unchanged (and is NOT auto-retried as a
        transport error)."""
        snap = _base_snapshot("reference")
        srv = CapacityServer(
            snap, port=0,
            admission=AdmissionController(rps=0.0001, burst=1.0),
        )
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                c.sweep(cpu_request_milli=[100],
                        mem_request_bytes=[10 ** 8], replicas=[1])
                with pytest.raises(OverloadedError):
                    c.sweep(cpu_request_milli=[100],
                            mem_request_bytes=[10 ** 8], replicas=[1])
        finally:
            srv.shutdown()

    def test_mutation_transport_failure_is_at_most_once(self):
        """A mutation whose transport dies MID-CALL must not be resent
        to a sibling (it may have executed)."""
        snap = _base_snapshot("reference")
        srv = CapacityServer(snap, port=0)
        srv.start()
        sibling = CapacityServer(snap, port=0)
        sibling.start()
        plan = FaultPlan(["drop_post"])  # executed, reply withheld
        proxy = FaultProxy(srv.address, plan).start()
        try:
            rs = ReplicaSet([proxy.address, sibling.address])
            try:
                with pytest.raises(Exception) as exc:
                    rs.update([])
                assert not isinstance(exc.value, ReplicaSetError)
                # The sibling never saw the mutation.
                assert plan.forwarded == 1
            finally:
                rs.close()
        finally:
            proxy.stop()
            srv.shutdown()
            sibling.shutdown()


class TestMonotonicity:
    def test_stale_answer_discarded_never_returned(self):
        """Endpoints at different generations: once the session has seen
        generation G, an endpoint still serving G-1 is rejected (stale),
        and with no fresh endpoint left the call raises StaleReadError
        rather than regress."""
        snap = _base_snapshot("reference")
        fresh = CapacityServer(snap, port=0)
        frozen = CapacityServer(snap, port=0)
        fresh.start()
        frozen.start()
        # fresh advances to generation 3; frozen stays at 1.
        g = snap
        for i in range(2):
            g = _next_generation(g, i)
            fresh.replace_snapshot(g)
        assert fresh.generation == 3 and frozen.generation == 1
        try:
            rs = ReplicaSet([fresh.address, frozen.address], rounds=1)
            try:
                rs.ping()
                assert rs.watermark == 3  # answered by fresh
                fresh.shutdown()  # only the stale endpoint remains
                with pytest.raises(StaleReadError):
                    rs.ping()
                stale = rs.registry.counter(
                    "kccap_replicaset_stale_rejected_total", ""
                )
                assert stale.value >= 1
            finally:
                rs.close()
        finally:
            frozen.shutdown()

    def test_watermark_monotone_across_failover(self):
        snap = _base_snapshot("reference")
        a = CapacityServer(snap, port=0)
        b = CapacityServer(snap, port=0)
        a.start()
        b.start()
        g2 = _next_generation(snap, 1)
        a.replace_snapshot(g2)
        b.replace_snapshot(g2)
        try:
            rs = ReplicaSet([a.address, b.address])
            try:
                seen = []
                for _ in range(6):
                    rs.ping()
                    seen.append(rs.watermark)
                a.shutdown()
                for _ in range(6):
                    rs.ping()
                    seen.append(rs.watermark)
                assert seen == sorted(seen)  # never regresses
            finally:
                rs.close()
        finally:
            b.shutdown()


class TestHedging:
    def test_hedge_wins_past_stalled_primary(self):
        """Primary stalled past its deadline by the proxy: the hedged
        attempt on the sibling answers inside the budget."""
        snap = _base_snapshot("reference")
        slow = CapacityServer(snap, port=0)
        fast = CapacityServer(snap, port=0)
        slow.start()
        fast.start()
        plan = FaultPlan(["stall"] * 50)
        proxy = FaultProxy(slow.address, plan, stall_s=3.0).start()
        try:
            rs = ReplicaSet(
                [proxy.address, fast.address],
                hedge=True,
                hedge_max_delay_s=0.1,
                timeout_s=5.0,
            )
            try:
                t0 = time.monotonic()
                r = rs.sweep(
                    cpu_request_milli=[100], mem_request_bytes=[10 ** 8],
                    replicas=[1], deadline_s=4.0,
                )
                elapsed = time.monotonic() - t0
                want, _ = _oracle_totals(snap, [100], [10 ** 8], [1])
                assert r["totals"] == want
                assert elapsed < 2.5  # did not ride out the 3 s stall
                hedges = rs.registry.counter(
                    "kccap_replicaset_hedges_total", ""
                )
                wins = rs.registry.counter(
                    "kccap_replicaset_hedge_wins_total", ""
                )
                assert hedges.value >= 1
                assert wins.value >= 1
            finally:
                rs.close()
        finally:
            proxy.stop()
            slow.shutdown()
            fast.shutdown()

    def test_mutations_never_hedged(self):
        snap = _base_snapshot("reference")
        a = CapacityServer(snap, port=0)
        b = CapacityServer(snap, port=0)
        a.start()
        b.start()
        try:
            rs = ReplicaSet(
                [a.address, b.address], hedge=True, hedge_max_delay_s=0.001
            )
            try:
                with pytest.raises(Exception):
                    rs.update([])  # .npz-less server refuses; that's fine
                hedges = rs.registry.counter(
                    "kccap_replicaset_hedges_total", ""
                )
                assert hedges.value == 0  # the mutation never hedged
            finally:
                rs.close()
        finally:
            a.shutdown()
            b.shutdown()


# ---------------------------------------------------------------------------
# The chaos suite
# ---------------------------------------------------------------------------
class _Plane:
    """One leader + two replicas, every link through a seeded fault
    proxy: the chaos harness."""

    def __init__(self, semantics, *, seed=0, n_nodes=24):
        self.snapshots = {}  # generation -> snapshot (the oracle's view)
        self.base = _base_snapshot(semantics, n=n_nodes, seed=seed)
        self.pub = PlanePublisher(heartbeat_s=0.2)
        self.leader = CapacityServer(
            self.base, port=0, plane=self.pub, batch_window_ms=0.0
        )
        self.leader.start()
        self.snapshots[self.leader.generation] = self.base
        self.replicas = []
        self.subs = []
        self.plane_proxies = []
        self.req_proxies = []
        for i in range(2):
            replica = CapacityServer(self.base, port=0, batch_window_ms=0.0)
            replica.start()
            # Garble the plane link deterministically (one plan per
            # replica, different phases).
            plan = FaultPlan.seeded(
                seed * 101 + i, 64, fault_rate=0.25,
                faults=("garbage", "drop_pre", "stall"),
            )
            pproxy = FaultProxy(
                self.pub.address, plan, stream=True, stall_s=0.1
            ).start()
            sub = PlaneSubscriber(
                pproxy.address, replica,
                stale_after_s=30.0, seed=i,
                reconnect_base_s=0.01, reconnect_max_s=0.05,
            )
            # And fault the request link too.
            rplan = FaultPlan.seeded(
                seed * 211 + i, 48, fault_rate=0.2,
                faults=("drop_pre", "partial", "garbage"),
            )
            rproxy = FaultProxy(replica.address, rplan).start()
            self.replicas.append(replica)
            self.subs.append(sub)
            self.plane_proxies.append(pproxy)
            self.req_proxies.append(rproxy)

    def publish(self, seed):
        snap = _next_generation(
            self.snapshots[self.leader.generation], seed
        )
        self.leader.replace_snapshot(snap)
        self.snapshots[self.leader.generation] = snap
        return self.leader.generation

    def wait_converged(self, generation, timeout_s=15.0):
        _wait_for(
            lambda: all(
                s.applied_generation >= generation for s in self.subs
            ),
            timeout_s=timeout_s,
            what=f"replicas at generation {generation}",
        )

    def endpoints(self):
        return [p.address for p in self.req_proxies]

    def close(self):
        for sub in self.subs:
            sub.stop()
        for p in self.plane_proxies + self.req_proxies:
            p.stop()
        for r in self.replicas:
            r.shutdown()
        self.pub.close()
        self.leader.shutdown()


SCENARIOS = dict(
    cpu=[100, 250, 900], mem=[10 ** 8, 3 * 10 ** 8, 10 ** 9],
    replicas=[1, 4, 16],
)


def _assert_answer_correct(plane, rs, result):
    """THE invariant: the answer must be bit-identical to the sequential
    oracle at its stamped generation — asserted for every response."""
    gen = rs.last_generation
    assert gen in plane.snapshots, f"unstamped/unknown generation {gen}"
    want_totals, want_sched = _oracle_totals(
        plane.snapshots[gen], SCENARIOS["cpu"], SCENARIOS["mem"],
        SCENARIOS["replicas"],
    )
    assert result["totals"] == want_totals
    assert result["schedulable"] == want_sched


@pytest.mark.parametrize("semantics", ["reference", "strict"])
class TestChaos:
    def _client(self, plane, **kw):
        kw.setdefault("connect_timeout_s", 1.0)
        kw.setdefault("timeout_s", 5.0)
        kw.setdefault("deadline_s", 8.0)
        kw.setdefault("rounds", 4)
        kw.setdefault(
            "retry_backoff",
            RetryPolicy(max_attempts=1, base_delay_s=0.01,
                        max_delay_s=0.05, seed=0),
        )
        kw.setdefault(
            "breaker_factory",
            lambda addr: CircuitBreaker(
                failure_threshold=3, recovery_timeout_s=0.1,
                name=f"{addr[0]}:{addr[1]}",
            ),
        )
        return ReplicaSet(plane.endpoints(), **kw)

    def test_zero_wrong_answers_under_garbled_links(self, semantics):
        """Faulted plane links AND faulted request links, generations
        churning between calls: every answer bit-exact at its stamped
        generation, watermark monotone throughout."""
        plane = _Plane(semantics, seed=3)
        rs = self._client(plane)
        try:
            watermarks = []
            for step in range(10):
                if step % 2 == 0 and step > 0:
                    gen = plane.publish(seed=1000 + step)
                    plane.wait_converged(gen)
                r = rs.sweep(
                    cpu_request_milli=SCENARIOS["cpu"],
                    mem_request_bytes=SCENARIOS["mem"],
                    replicas=SCENARIOS["replicas"],
                )
                _assert_answer_correct(plane, rs, r)
                watermarks.append(rs.watermark)
            assert watermarks == sorted(watermarks)
            # The chaos was real: at least one fault fired per link kind.
            assert any(
                sum(p.plan.injected.values()) > 0
                for p in plane.plane_proxies
            )
            assert any(
                sum(p.plan.injected.values()) > 0
                for p in plane.req_proxies
            )
        finally:
            rs.close()
            plane.close()

    def test_replica_kill_mid_sweep(self, semantics):
        """A replica dies while sweeps are in flight from 4 threads:
        every completed answer is still oracle-exact at its stamped
        generation; no stamped generation regresses per thread."""
        plane = _Plane(semantics, seed=5)
        rs = self._client(plane)
        errors = []
        answers = []
        lock = threading.Lock()
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                try:
                    r = rs.sweep(
                        cpu_request_milli=SCENARIOS["cpu"],
                        mem_request_bytes=SCENARIOS["mem"],
                        replicas=SCENARIOS["replicas"],
                    )
                    with lock:
                        answers.append((rs.last_generation, r))
                except Exception as e:  # noqa: BLE001 - tallied below
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

        try:
            gen = plane.publish(seed=77)
            plane.wait_converged(gen)
            threads = [threading.Thread(target=worker) for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            # The kill: replica 0 vanishes mid-run (its request proxy
            # keeps refusing connects afterwards).
            plane.subs[0].stop()
            plane.replicas[0].shutdown()
            time.sleep(0.6)
            stop.set()
            for t in threads:
                t.join(10.0)
            assert answers, "no sweep completed at all"
            # ZERO wrong answers: every completed response bit-exact at
            # its stamped generation.
            for gen_stamp, r in answers:
                want_totals, want_sched = _oracle_totals(
                    plane.snapshots[gen_stamp], SCENARIOS["cpu"],
                    SCENARIOS["mem"], SCENARIOS["replicas"],
                )
                assert r["totals"] == want_totals
                assert r["schedulable"] == want_sched
            # A few calls may fail while the breaker learns — but the
            # set must keep answering overall (the surviving replica).
            assert len(errors) < len(answers)
        finally:
            stop.set()
            rs.close()
            plane.close()

    def test_plane_stall_bounded_staleness(self, semantics):
        """One replica's plane stream stalls: it freezes at an old
        generation and — past ``stale_after_s`` on its (injected) clock
        — reports itself stale.  A probing client demotes it, observes
        the new generation from the healthy replica, and from then on
        the frozen replica's old answers are REJECTED by the watermark:
        the session never travels back in time.  Deterministic — the
        staleness bound runs on a fake clock, not real sleeps."""
        base = _base_snapshot(semantics, n=24, seed=9)
        snapshots = {}
        pub = PlanePublisher(heartbeat_s=3600.0)  # silence = the stall
        leader = CapacityServer(base, port=0, plane=pub, batch_window_ms=0.0)
        leader.start()
        snapshots[leader.generation] = base
        clocks = [[0.0], [0.0]]  # one injectable clock per replica
        replicas, subs = [], []
        for i in range(2):
            r = CapacityServer(base, port=0, batch_window_ms=0.0)
            r.start()
            subs.append(
                PlaneSubscriber(
                    pub.address, r, stale_after_s=5.0, seed=i,
                    clock=lambda i=i: clocks[i][0],
                )
            )
            replicas.append(r)
        rs = ReplicaSet([r.address for r in replicas], rounds=2)
        try:
            _wait_for(
                lambda: all(s.applied_generation >= 1 for s in subs),
                what="initial checkpoints",
            )
            r0 = rs.sweep(
                cpu_request_milli=SCENARIOS["cpu"],
                mem_request_bytes=SCENARIOS["mem"],
                replicas=SCENARIOS["replicas"],
            )
            gen_stamp = rs.last_generation
            want, _ = _oracle_totals(
                snapshots[gen_stamp], SCENARIOS["cpu"], SCENARIOS["mem"],
                SCENARIOS["replicas"],
            )
            assert r0["totals"] == want
            # THE STALL: sever replica 0's plane link; publish a new
            # generation only replica 1 receives.
            subs[0].stop()
            frozen_at = subs[0].applied_generation
            snap2 = _next_generation(base, 12)
            leader.replace_snapshot(snap2)
            gen2 = leader.generation
            snapshots[gen2] = snap2
            _wait_for(
                lambda: subs[1].applied_generation >= gen2,
                what="healthy replica converges",
            )
            assert subs[0].applied_generation == frozen_at < gen2
            # Bounded staleness detection: past stale_after_s of silence
            # the frozen replica SAYS SO (no real sleep — fake clock).
            clocks[0][0] += 5.1
            assert subs[0].stale and not subs[1].stale
            probe = {e["endpoint"]: e for e in rs.probe()}
            assert rs.stats()["endpoints"][0]["stale"] is True
            assert probe  # probe reached the endpoints
            # The demoted rotation now answers from the healthy replica:
            # the session observes gen2...
            r1 = rs.sweep(
                cpu_request_milli=SCENARIOS["cpu"],
                mem_request_bytes=SCENARIOS["mem"],
                replicas=SCENARIOS["replicas"],
            )
            assert rs.last_generation == gen2
            want2, _ = _oracle_totals(
                snap2, SCENARIOS["cpu"], SCENARIOS["mem"],
                SCENARIOS["replicas"],
            )
            assert r1["totals"] == want2
            # ...and can never regress below it: every further answer is
            # gen2-stamped (the frozen replica's gen-1 answers are
            # watermark-rejected whenever routing lands on it).
            for _ in range(6):
                r = rs.sweep(
                    cpu_request_milli=SCENARIOS["cpu"],
                    mem_request_bytes=SCENARIOS["mem"],
                    replicas=SCENARIOS["replicas"],
                )
                assert rs.last_generation == gen2
                assert r["totals"] == want2
            assert rs.watermark == gen2
        finally:
            rs.close()
            for s in subs:
                s.stop()
            for r in replicas:
                r.shutdown()
            pub.close()
            leader.shutdown()


@pytest.mark.slow
class TestSustainedLoad:
    def test_fixed_rps_with_replica_kill_recovers(self):
        """Open-loop fixed-rps smoke (the bench row's little sibling):
        mid-run replica kill; the set keeps answering, every answer
        oracle-exact, and the post-kill error rate returns to zero
        (recovery, not collapse)."""
        plane = _Plane("reference", seed=21)
        rs = ReplicaSet(
            plane.endpoints(),
            connect_timeout_s=1.0, timeout_s=5.0, deadline_s=5.0,
            rounds=4,
        )
        rps, duration_s = 40.0, 3.0
        outcomes = []  # (t_offset, ok, gen, result|err)
        lock = threading.Lock()

        def issue(t_offset):
            try:
                r = rs.sweep(
                    cpu_request_milli=SCENARIOS["cpu"],
                    mem_request_bytes=SCENARIOS["mem"],
                    replicas=SCENARIOS["replicas"],
                )
                with lock:
                    outcomes.append((t_offset, True, rs.last_generation, r))
            except Exception as e:  # noqa: BLE001 - tallied below
                with lock:
                    outcomes.append((t_offset, False, None, str(e)))

        try:
            gen = plane.publish(seed=31)
            plane.wait_converged(gen)
            t0 = time.monotonic()
            killed = False
            i = 0
            while True:
                t_offset = i / rps
                if t_offset > duration_s:
                    break
                now = time.monotonic() - t0
                if t_offset > now:
                    time.sleep(t_offset - now)
                if not killed and t_offset >= duration_s / 3:
                    plane.subs[0].stop()
                    plane.replicas[0].shutdown()
                    killed = True
                threading.Thread(
                    target=issue, args=(t_offset,), daemon=True
                ).start()
                i += 1
            deadline = time.monotonic() + 15
            while len(outcomes) < i and time.monotonic() < deadline:
                time.sleep(0.05)
            assert len(outcomes) == i, "requests lost without outcome"
            for t_offset, ok, gen_stamp, payload in outcomes:
                if ok:
                    want_totals, _ = _oracle_totals(
                        plane.snapshots[gen_stamp], SCENARIOS["cpu"],
                        SCENARIOS["mem"], SCENARIOS["replicas"],
                    )
                    assert payload["totals"] == want_totals
            oks = sum(1 for o in outcomes if o[1])
            assert oks > 0.8 * i  # the set kept serving through the kill
            # Recovery: the final third is error-free (breaker learned).
            tail = [o for o in outcomes if o[0] > 2 * duration_s / 3]
            assert tail and all(o[1] for o in tail)
        finally:
            rs.close()
            plane.close()
