"""PodDisruptionBudget gate (``pdb.py``) and its drain integration."""

import numpy as np
import pytest

from kubernetesclustercapacity_tpu.models import CapacityModel
from kubernetesclustercapacity_tpu.pdb import (
    blocked_evictions,
    budget_statuses,
)
from kubernetesclustercapacity_tpu.snapshot import snapshot_from_fixture


def _pod(name, ns, node, labels=None, phase="Running"):
    return {"name": name, "namespace": ns, "nodeName": node, "phase": phase,
            "labels": labels or {}, "containers": [{"resources": {
                "requests": {"cpu": "100m", "memory": "67108864"}}}]}


def _node(name, cpu="8"):
    return {"name": name,
            "allocatable": {"cpu": cpu, "memory": "16777216Ki", "pods": "20"},
            "conditions": [{"type": "Ready", "status": "True"}]}


@pytest.fixture()
def pdb_fixture():
    return {
        "nodes": [_node("a"), _node("b")],
        "pods": [
            _pod("db-0", "prod", "a", {"app": "db"}),
            _pod("db-1", "prod", "b", {"app": "db"}),
            _pod("web-0", "prod", "a", {"app": "web"}),
            _pod("db-x", "staging", "a", {"app": "db"}),  # other namespace
        ],
        "pdbs": [{
            "name": "db-pdb", "namespace": "prod",
            "selector": {"matchLabels": {"app": "db"}},
            "minAvailable": 2,
        }],
    }


class TestBudgetMath:
    def test_min_available_exhausted(self, pdb_fixture):
        (s,) = budget_statuses(pdb_fixture)
        assert (s.expected, s.healthy) == (2, 2)  # prod/db only
        assert s.desired_healthy == 2 and s.allowed_disruptions == 0

    def test_min_available_with_slack(self, pdb_fixture):
        pdb_fixture["pdbs"][0]["minAvailable"] = 1
        (s,) = budget_statuses(pdb_fixture)
        assert s.allowed_disruptions == 1

    def test_max_unavailable(self, pdb_fixture):
        del pdb_fixture["pdbs"][0]["minAvailable"]
        pdb_fixture["pdbs"][0]["maxUnavailable"] = 1
        (s,) = budget_statuses(pdb_fixture)
        assert s.desired_healthy == 1 and s.allowed_disruptions == 1

    def test_percentage_rounds_up(self, pdb_fixture):
        pdb_fixture["pdbs"][0]["minAvailable"] = "51%"
        (s,) = budget_statuses(pdb_fixture)
        assert s.desired_healthy == 2  # ceil(1.02)
        assert s.allowed_disruptions == 0

    def test_pending_pod_counts_expected_not_healthy(self, pdb_fixture):
        pdb_fixture["pods"].append(
            _pod("db-2", "prod", "", {"app": "db"}, phase="Pending"))
        pdb_fixture["pdbs"][0]["minAvailable"] = "50%"
        (s,) = budget_statuses(pdb_fixture)
        assert (s.expected, s.healthy) == (3, 2)
        assert s.desired_healthy == 2 and s.allowed_disruptions == 0

    def test_both_fields_rejected(self, pdb_fixture):
        pdb_fixture["pdbs"][0]["maxUnavailable"] = 1
        with pytest.raises(ValueError, match="exactly one"):
            budget_statuses(pdb_fixture)

    def test_empty_selector_matches_namespace(self, pdb_fixture):
        pdb_fixture["pdbs"][0]["selector"] = {}
        (s,) = budget_statuses(pdb_fixture)
        assert s.expected == 3  # every prod pod, not staging

    def test_match_expressions(self, pdb_fixture):
        pdb_fixture["pdbs"][0]["selector"] = {
            "matchExpressions": [
                {"key": "app", "operator": "In", "values": ["db", "cache"]}
            ]
        }
        (s,) = budget_statuses(pdb_fixture)
        assert s.expected == 2

    @pytest.mark.parametrize("field", ["minAvailable", "maxUnavailable"])
    @pytest.mark.parametrize("bad", [-1, "-25%"])
    def test_negative_intstr_rejected(self, pdb_fixture, field, bad):
        """ISSUE 1 satellite: a negative minAvailable used to silently
        yield allowed_disruptions == healthy — a protection budget that
        waves every eviction through."""
        pdb = pdb_fixture["pdbs"][0]
        pdb.pop("minAvailable", None)
        pdb[field] = bad
        with pytest.raises(ValueError, match=">= 0"):
            budget_statuses(pdb_fixture)

    def test_validate_selector_checks_expressions_unconditionally(self):
        from kubernetesclustercapacity_tpu.pdb import validate_selector

        # The poison shape: non-empty matchLabels would short-circuit a
        # probe-pod evaluation before the malformed expression runs.
        bad = {
            "matchLabels": {"app": "db"},
            "matchExpressions": [{"key": "k", "operator": "Sideways"}],
        }
        with pytest.raises(ValueError, match="Sideways"):
            validate_selector(bad)
        with pytest.raises(ValueError, match="non-empty values"):
            validate_selector(
                {"matchExpressions": [{"key": "k", "operator": "In",
                                       "values": []}]}
            )
        with pytest.raises(ValueError, match="must not carry values"):
            validate_selector(
                {"matchExpressions": [{"key": "k", "operator": "Exists",
                                       "values": ["x"]}]}
            )
        # Well-formed selectors (including empty) pass.
        validate_selector({})
        validate_selector({
            "matchLabels": {"a": "b"},
            "matchExpressions": [
                {"key": "k", "operator": "NotIn", "values": ["v"]},
                {"key": "k2", "operator": "DoesNotExist"},
            ],
        })

    def test_blocked_evictions_scoped(self, pdb_fixture):
        blocked = blocked_evictions(
            pdb_fixture,
            ["prod/db-0", "prod/web-0", "staging/db-x"],
        )
        assert blocked == {"prod/db-0": ["db-pdb"]}

    def test_no_pdbs_no_blocks(self):
        assert blocked_evictions({"pods": []}, ["a/b"]) == {}

    def test_multi_coverage_blocks_regardless_of_allowance(self, pdb_fixture):
        """The eviction API errors on >1 covering PDB even with slack."""
        pdb_fixture["pdbs"][0]["minAvailable"] = 0  # ample allowance
        pdb_fixture["pdbs"].append({
            "name": "db-pdb-2", "namespace": "prod",
            "selector": {"matchLabels": {"app": "db"}},
            "maxUnavailable": 2,  # ample allowance too
        })
        blocked = blocked_evictions(pdb_fixture, ["prod/db-0", "prod/web-0"])
        assert blocked == {"prod/db-0": ["db-pdb", "db-pdb-2"]}


class TestDrainIntegration:
    def _drain(self, fx, node="a"):
        snap = snapshot_from_fixture(fx, semantics="strict")
        return CapacityModel(snap, mode="strict", fixture=fx).drain(node)

    def test_exhausted_budget_blocks_drain(self, pdb_fixture):
        result = self._drain(pdb_fixture)
        assert result.blocked == {"prod/db-0": ["db-pdb"]}
        assert not result.evictable
        # The plan still shows where the pod WOULD go.
        assert result.by_pod()["prod/db-0"] == "b"

    def test_budget_with_slack_allows_drain(self, pdb_fixture):
        pdb_fixture["pdbs"][0]["minAvailable"] = 1
        result = self._drain(pdb_fixture)
        assert result.blocked == {} and result.evictable

    def test_wire_carries_blocked_and_survives_update(self, pdb_fixture):
        from kubernetesclustercapacity_tpu.service import (
            CapacityClient,
            CapacityServer,
        )

        snap = snapshot_from_fixture(pdb_fixture, semantics="strict")
        srv = CapacityServer(snap, port=0, fixture=pdb_fixture)
        srv.start()
        try:
            with CapacityClient(*srv.address) as c:
                r = c.drain("a")
                assert not r["evictable"]
                assert r["blocked"] == {"prod/db-0": ["db-pdb"]}
                # A store rematerialization must keep the budgets: add an
                # unrelated pod, then re-drain.
                c.update([{"type": "ADDED", "kind": "Pod", "object":
                           _pod("web-1", "prod", "b", {"app": "web"})}])
                r2 = c.drain("a")
                assert r2["blocked"] == {"prod/db-0": ["db-pdb"]}
        finally:
            srv.shutdown()

    def test_cli_renders_blocked(self, capsys, tmp_path, pdb_fixture):
        import json

        from kubernetesclustercapacity_tpu.cli import main

        path = tmp_path / "c.json"
        path.write_text(json.dumps(pdb_fixture))
        code = main(["-snapshot", str(path), "-semantics", "strict",
                     "-drain", "a"])
        out = capsys.readouterr().out
        assert code == 1
        assert "[BLOCKED by PDB db-pdb]" in out
        assert "blocked by disruption budgets" in out


class TestStoreEvents:
    def test_pdb_events_upsert_and_delete(self, pdb_fixture):
        from kubernetesclustercapacity_tpu.store import ClusterStore

        store = ClusterStore(pdb_fixture, semantics="strict")
        assert store.has_pdb("prod", "db-pdb")
        store.apply_event({
            "type": "MODIFIED", "kind": "PodDisruptionBudget",
            "object": {"name": "db-pdb", "namespace": "prod",
                       "selector": {"matchLabels": {"app": "db"}},
                       "minAvailable": 1},
        })
        view = store.fixture_view()
        assert view["pdbs"][0]["minAvailable"] == 1
        store.apply_event({
            "type": "DELETED", "kind": "PodDisruptionBudget",
            "object": {"name": "db-pdb", "namespace": "prod"},
        })
        assert "pdbs" not in store.fixture_view()

    @pytest.mark.parametrize("bad", [
        # both fields (API forbids)
        {"minAvailable": 1, "maxUnavailable": 1},
        # selector faults must surface at ADMISSION, not at drain time
        {"minAvailable": 1, "selector": {"matchExpressions": [
            {"key": "app", "operator": "Wat"}]}},
        # ...including when non-empty matchLabels would short-circuit a
        # probe-pod evaluation before the malformed expression ever ran
        # (ISSUE 1 satellite: store.py _validate_pdb)
        {"minAvailable": 1, "selector": {
            "matchLabels": {"app": "db"},
            "matchExpressions": [{"key": "app", "operator": "Wat"}]}},
        {"minAvailable": 1, "selector": {"matchLabels": "notadict"}},
        {"minAvailable": "x%"},
        # negative budgets (silently evictable-everything before)
        {"minAvailable": -2},
        {"maxUnavailable": "-10%"},
    ])
    def test_malformed_pdb_event_rejected(self, pdb_fixture, bad):
        from kubernetesclustercapacity_tpu.store import (
            ClusterStore,
            StoreError,
        )

        store = ClusterStore(pdb_fixture, semantics="strict")
        with pytest.raises(StoreError, match="malformed PDB"):
            store.apply_event({
                "type": "ADDED", "kind": "PodDisruptionBudget",
                "object": {"name": "bad", "namespace": "prod", **bad},
            })
        # The rejected event left raw state intact, and drain still works.
        view = store.fixture_view()
        assert [b["name"] for b in view["pdbs"]] == ["db-pdb"]

    def test_duplicate_pdbs_rejected(self, pdb_fixture):
        from kubernetesclustercapacity_tpu.store import (
            ClusterStore,
            StoreError,
        )

        pdb_fixture["pdbs"].append(dict(pdb_fixture["pdbs"][0]))
        with pytest.raises(StoreError, match="duplicate PDB"):
            ClusterStore(pdb_fixture, semantics="strict")


class TestFollowerStream:
    def test_follower_lists_and_streams_pdbs(self, pdb_fixture):
        """List picks the budgets up; a watch event updates them; the
        degrade path (no policy API) leaves the follower healthy."""
        import json as _json

        from kubernetesclustercapacity_tpu.follower import ClusterFollower
        from kubernetesclustercapacity_tpu.kubeapi import (
            PDB_PATH,
            KubeClient,
            KubeConfig,
        )
        from test_kubeapi import MockApiserver, _k8s_pdb

        server = MockApiserver(pdb_fixture, require_token="tok")
        updated = dict(pdb_fixture["pdbs"][0], minAvailable=1)
        ev_obj = _k8s_pdb(updated)
        ev_obj["metadata"]["resourceVersion"] = "901"
        server.watch_streams = {
            PDB_PATH: [[{"type": "MODIFIED", "object": ev_obj}]],
        }
        cfg = KubeConfig(f"http://127.0.0.1:{server.port}", token="tok")
        f = ClusterFollower(
            client_factory=lambda: KubeClient(cfg),
            semantics="strict", stop_on_idle_window=True,
        ).start()
        try:
            assert f.wait_synced(5)
            f.join(5)
            view = f.fixture_view()
            assert view["pdbs"] == [
                _json.loads(_json.dumps(updated))
            ]
        finally:
            f.stop()
            server.close()

    def test_follower_degrades_without_policy_api(self):
        from kubernetesclustercapacity_tpu.follower import ClusterFollower
        from kubernetesclustercapacity_tpu.kubeapi import (
            KubeClient,
            KubeConfig,
        )
        from test_kubeapi import MockApiserver

        fx = {"nodes": [_node("a")], "pods": []}  # no pdbs → policy 404s
        server = MockApiserver(fx, require_token="tok")
        cfg = KubeConfig(f"http://127.0.0.1:{server.port}", token="tok")
        f = ClusterFollower(
            client_factory=lambda: KubeClient(cfg),
            semantics="strict", stop_on_idle_window=True,
        ).start()
        try:
            assert f.wait_synced(5)
            assert f._pdb_unavailable
            assert "pdbs" not in f.fixture_view()
            assert f.fatal is None
        finally:
            f.stop()
            server.close()


class TestLiveConversion:
    def test_pdb_to_fixture(self):
        from kubernetesclustercapacity_tpu.kubeapi import pdb_to_fixture

        rest = {
            "metadata": {"name": "db", "namespace": "prod"},
            "spec": {"selector": {"matchLabels": {"app": "db"}},
                     "minAvailable": "50%"},
        }
        out = pdb_to_fixture(rest)
        assert out == {"name": "db", "namespace": "prod",
                       "selector": {"matchLabels": {"app": "db"}},
                       "minAvailable": "50%"}
        rest["spec"] = {"selector": {}, "maxUnavailable": 1}
        assert pdb_to_fixture(rest)["maxUnavailable"] == 1
