"""Sharded-sweep tests on the 8-device virtual CPU mesh (conftest sets it up)."""

import jax
import numpy as np
import pytest

from kubernetesclustercapacity_tpu.ops.fit import snapshot_device_arrays, sweep_snapshot
from kubernetesclustercapacity_tpu.parallel import make_mesh, sweep_gspmd, sweep_shard_map
from kubernetesclustercapacity_tpu.scenario import random_scenario_grid
from kubernetesclustercapacity_tpu.snapshot import synthetic_snapshot


@pytest.fixture(scope="module")
def snap():
    return synthetic_snapshot(503, seed=21)  # prime: forces node padding


@pytest.fixture(scope="module")
def grid():
    return random_scenario_grid(97, seed=22)  # prime: forces scenario padding


@pytest.fixture(scope="module")
def baseline(snap, grid):
    return sweep_snapshot(snap, grid)


def _arrays(snap):
    return tuple(np.asarray(a) for a in snapshot_device_arrays(snap))


def test_eight_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("sp,np_", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_shard_map_matches_unsharded(snap, grid, baseline, sp, np_):
    plan = make_mesh(sp, np_)
    totals, sched = sweep_shard_map(
        plan, _arrays(snap), grid.cpu_request_milli, grid.mem_request_bytes,
        grid.replicas,
    )
    np.testing.assert_array_equal(totals, baseline[0])
    np.testing.assert_array_equal(sched, baseline[1])


@pytest.mark.parametrize("sp,np_", [(8, 1), (2, 4)])
def test_gspmd_matches_unsharded(snap, grid, baseline, sp, np_):
    plan = make_mesh(sp, np_)
    totals, sched = sweep_gspmd(
        plan, _arrays(snap), grid.cpu_request_milli, grid.mem_request_bytes,
        grid.replicas,
    )
    np.testing.assert_array_equal(totals, baseline[0])
    np.testing.assert_array_equal(sched, baseline[1])


def test_strict_mode_sharded(snap, grid):
    plan = make_mesh(4, 2)
    ref_totals, _ = sweep_snapshot(snap, grid, mode="strict")
    totals, _ = sweep_shard_map(
        plan, _arrays(snap), grid.cpu_request_milli, grid.mem_request_bytes,
        grid.replicas, mode="strict",
    )
    np.testing.assert_array_equal(totals, ref_totals)


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh(3, 2)  # 6 != 8 devices


def test_mesh_padding_math():
    plan = make_mesh(4, 2)
    assert plan.pad_scenarios(97) == 100
    assert plan.pad_nodes(503) == 504
    assert plan.pad_nodes(504) == 504


class TestMultihost:
    """Single-process path of the DCN layer (same program runs on a pod)."""

    def test_initialize_is_noop_single_process(self):
        from kubernetesclustercapacity_tpu.parallel import multihost

        assert multihost.initialize() is False
        assert multihost.initialize(num_processes=1) is False

    def test_scenario_block_partition(self):
        from kubernetesclustercapacity_tpu.parallel.multihost import (
            scenario_block,
        )

        for total, pcount in [(97, 4), (8, 8), (5, 8), (1000, 3)]:
            blocks = [scenario_block(total, p, pcount) for p in range(pcount)]
            covered = []
            for start, stop in blocks:
                assert 0 <= start <= stop <= total
                covered.extend(range(start, stop))
            assert covered == list(range(total))  # exact disjoint cover

    def test_sweep_multihost_matches_unsharded(self, snap, grid, baseline):
        from kubernetesclustercapacity_tpu.parallel.multihost import (
            sweep_multihost,
        )

        totals, sched = sweep_multihost(
            _arrays(snap), grid.cpu_request_milli, grid.mem_request_bytes,
            grid.replicas,
        )
        np.testing.assert_array_equal(totals, baseline[0])
        np.testing.assert_array_equal(sched, baseline[1])

    def test_sweep_multihost_multi_matches_unsharded(self, snap, grid):
        from kubernetesclustercapacity_tpu.ops.fit import sweep_grid_multi
        from kubernetesclustercapacity_tpu.parallel.multihost import (
            sweep_multihost_multi,
        )

        from kubernetesclustercapacity_tpu.fixtures import (
            synthetic_multi_workload,
        )

        alloc_rn, used_rn, reqs_sr, reps = synthetic_multi_workload(
            snap, grid.size, seed=44
        )
        totals, sched = sweep_multihost_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict",
        )
        exact = sweep_grid_multi(
            alloc_rn, used_rn, snap.alloc_pods, snap.pods_count,
            snap.healthy, reqs_sr, reps, mode="strict",
        )
        np.testing.assert_array_equal(totals, np.asarray(exact[0]))
        np.testing.assert_array_equal(sched, np.asarray(exact[1]))

    def test_gather_false_returns_local_block(self, snap, grid, baseline):
        from kubernetesclustercapacity_tpu.parallel.multihost import (
            sweep_multihost,
        )

        totals, _ = sweep_multihost(
            _arrays(snap), grid.cpu_request_milli, grid.mem_request_bytes,
            grid.replicas, gather=False,
        )
        # Single process owns the whole grid.
        np.testing.assert_array_equal(totals, baseline[0])

    def test_strict_mode(self, snap, grid):
        from kubernetesclustercapacity_tpu.parallel.multihost import (
            sweep_multihost,
        )

        ref_totals, _ = sweep_snapshot(snap, grid, mode="strict")
        totals, _ = sweep_multihost(
            _arrays(snap), grid.cpu_request_milli, grid.mem_request_bytes,
            grid.replicas, mode="strict",
        )
        np.testing.assert_array_equal(totals, ref_totals)


class TestMultihostDCN:
    """Actually EXECUTE the multi-process allgather path (VERDICT r1 #3):
    two jax.distributed CPU processes over a localhost coordinator."""

    def test_two_process_gather_matches_single_host(self):
        import os
        import socket
        import subprocess
        import sys

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(repo_root, "tests", "multihost_worker.py")
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            PYTHONPATH=repo_root,  # script launch: package resolves from root
        )
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(port), str(i), "2"],
                env=env,
                cwd=repo_root,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
            for i in range(2)
        ]
        try:
            results = [p.communicate(timeout=240) for p in procs]
        except subprocess.TimeoutExpired:
            # One worker wedged (e.g. its peer crashed pre-rendezvous):
            # kill BOTH and surface whatever stderr exists — a bare
            # TimeoutExpired would mask the real failure and leak live
            # processes holding the coordinator port.
            for p in procs:
                p.kill()
            results = [p.communicate() for p in procs]
            raise AssertionError(
                "multihost worker timed out; stderr:\n"
                + "\n---\n".join(err for _, err in results)
            )
        for i, (p, (out, err)) in enumerate(zip(procs, results)):
            assert p.returncode == 0, f"process {i} failed:\n{err}"
            assert f"OK {i}" in out


class TestMillionNodeScale:
    """The node axis exists for "≥ millions of nodes" (parallel/mesh.py):
    prove the sharded paths stay bit-exact at that scale against the
    single-device kernel — shard_map over a pure node-axis (1x8) mesh,
    GSPMD over a mixed (2x4) mesh.  (The single-chip 1M perf number lives
    in bench.py as nodes_1m_per_sweep_ms.)"""

    @pytest.fixture(scope="class")
    def snap1m(self):
        return synthetic_snapshot(1_000_003, seed=31)  # prime: pads node axis

    @pytest.fixture(scope="class")
    def grid1m(self):
        return random_scenario_grid(8, seed=32)

    @pytest.fixture(scope="class")
    def baseline1m(self, snap1m, grid1m):
        return sweep_snapshot(snap1m, grid1m)

    def test_shard_map_node_axis_1m(self, snap1m, grid1m, baseline1m):
        plan = make_mesh(1, 8)
        totals, sched = sweep_shard_map(
            plan, _arrays(snap1m), grid1m.cpu_request_milli,
            grid1m.mem_request_bytes, grid1m.replicas,
        )
        np.testing.assert_array_equal(totals, baseline1m[0])
        np.testing.assert_array_equal(sched, baseline1m[1])

    def test_gspmd_node_axis_1m(self, snap1m, grid1m, baseline1m):
        plan = make_mesh(2, 4)
        totals, sched = sweep_gspmd(
            plan, _arrays(snap1m), grid1m.cpu_request_milli,
            grid1m.mem_request_bytes, grid1m.replicas,
        )
        np.testing.assert_array_equal(totals, baseline1m[0])
        np.testing.assert_array_equal(sched, baseline1m[1])
